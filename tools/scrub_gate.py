#!/usr/bin/env python
"""Scrub gate (tools/check.sh): every integrity fault site is injected,
detected within the cycle budget, auto-repaired, and the post-repair
state is proven byte-identical to the host truth.

Three self-contained drills, each against a real store/engine (no
mocks of the scrubbed surfaces):

- ``scrub.device_bitflip`` — a closure engine serves a poisoned D cell;
  the row-sample scrub must flag it, reset residency, and the engine's
  batch answers must again equal the host BFS oracle's exactly;
- ``wal.bitrot`` — one byte flipped inside a sealed WAL segment; the
  rolling rescan must flag the segment, re-anchor durability with a
  fresh checkpoint (pruning the damaged segment), and a cold
  ``recover_store`` must reproduce the live tuple set + version;
- ``replica.skip_delta`` — a follower silently drops a delta (version
  advances, tuples don't, lag reads 0); the anti-entropy digest compare
  must flag the divergent chunk and the reseed repair must reconverge
  the follower to the leader's exact tuple set.

Plus: the keto_scrub_* metric families must all appear on an exposition
after one cycle, and a clean store must scrub clean (no repair churn).

Exit 0 = all drills detected + repaired + reconverged; exit 1 with a
reason otherwise. Loopback aiohttp only for the replica leg; no device,
a few seconds of runtime.
"""

import json
import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from keto_tpu.engine import CheckEngine  # noqa: E402
from keto_tpu.engine.closure import ClosureCheckEngine  # noqa: E402
from keto_tpu.engine.scrub import (  # noqa: E402
    ACTION_CHECKPOINT_REBUILD,
    ACTION_RESEED,
    ACTION_RESET_RESIDENCY,
    KIND_DEVICE,
    KIND_REPLICA,
    KIND_WAL,
    ScrubDaemon,
)
from keto_tpu.faults import FAULTS  # noqa: E402
from keto_tpu.graph import SnapshotManager  # noqa: E402
from keto_tpu.relationtuple import RelationTuple  # noqa: E402
from keto_tpu.store import InMemoryTupleStore  # noqa: E402
from keto_tpu.telemetry import MetricsRegistry  # noqa: E402

# a fault must be caught within this many cycles of being injected —
# the ISSUE's detection-latency budget for the always-on scrub plane
CYCLE_BUDGET = 3

t = RelationTuple.from_string


def fail(msg: str) -> None:
    print(f"SCRUB GATE FAIL: {msg}")
    sys.exit(1)


def step_until(daemon: ScrubDaemon, kind: str) -> int:
    """Step until ``kind`` shows a mismatch; cycles taken, or fail."""
    before = daemon.mismatches.get(kind, 0)
    for cycle in range(1, CYCLE_BUDGET + 1):
        daemon.step()
        if daemon.mismatches.get(kind, 0) > before:
            return cycle
    fail(
        f"{kind}: no mismatch detected within {CYCLE_BUDGET} cycles "
        f"(snapshot: {daemon.snapshot()})"
    )
    return 0  # unreachable


# -- drill 1: device residency bitflip ----------------------------------------


def drill_device() -> None:
    store = InMemoryTupleStore()
    tuples = []
    for g in range(4):
        tuples.append(t(f"n:doc{g}#view@(n:group{g}#member)"))
        for u in range(6):
            tuples.append(t(f"n:group{g}#member@user{g}_{u}"))
    tuples.append(t("n:group0#member@(n:group1#member)"))
    store.write_relation_tuples(*tuples)
    eng = ClosureCheckEngine(SnapshotManager(store), max_depth=5)
    oracle = CheckEngine(store, max_depth=5)
    reqs = [
        t(f"n:doc{g}#view@user{h}_{u}")
        for g in range(4)
        for h in range(4)
        for u in range(6)
    ]
    baseline = oracle.batch_check(reqs)
    if eng.batch_check(reqs) != baseline:
        fail("device: engine disagrees with oracle BEFORE the drill")

    metrics = MetricsRegistry()
    daemon = ScrubDaemon(
        engine_fn=lambda: eng,
        store_fn=lambda: store,
        oracle_fn=lambda: oracle,
        version_fn=lambda: store.version,
        metrics=metrics,
        interval_s=999.0,
        sample_rows=4096,  # >= m: every row sampled, detection is certain
        seed=7,
    )
    # clean store must scrub clean: zero repairs, last_clean advances
    ev = daemon.step()
    if not ev.get("clean"):
        fail(f"device: clean store scrubbed dirty: {ev}")
    if daemon.repairs:
        fail(f"device: clean cycle applied repairs: {daemon.repairs}")
    if daemon.last_clean_version != store.version:
        fail("device: last_clean_version did not advance on a clean cycle")

    FAULTS.arm("scrub.device_bitflip", 1)
    cycles = step_until(daemon, KIND_DEVICE)
    if not daemon.repairs.get(ACTION_RESET_RESIDENCY):
        fail(f"device: no {ACTION_RESET_RESIDENCY} repair: {daemon.repairs}")
    if eng.batch_check(reqs) != baseline:
        fail("device: post-repair answers differ from the host oracle")
    ev = daemon.step()
    if not ev.get("clean"):
        fail(f"device: cycle after repair not clean: {ev}")

    # the metric families must be on the wire after real traffic
    text = metrics.expose()
    for fam in (
        "keto_scrub_cycles_total",
        "keto_scrub_mismatches_total",
        "keto_scrub_repairs_total",
        "keto_scrub_last_clean_version",
    ):
        if fam not in text:
            fail(f"metrics: family {fam} missing from exposition")
    print(
        f"scrub gate: device_bitflip detected in {cycles} cycle(s), "
        "repaired, answers byte-identical"
    )


# -- drill 2: WAL bitrot ------------------------------------------------------


def drill_wal(tmp: str) -> None:
    from keto_tpu.store.durable import DurableTupleStore, recover_store
    from keto_tpu.store.wal import sealed_segments

    wal_dir = os.path.join(tmp, "wal")
    store = DurableTupleStore(
        InMemoryTupleStore(),
        wal_dir,
        sync="always",
        segment_bytes=512,  # tiny segments: writes below seal several
    )
    for i in range(40):
        store.write_relation_tuples(t(f"n:doc{i}#view@user{i}"))
    if not sealed_segments(wal_dir):
        fail("wal: no sealed segments after 40 writes at 512B segments")

    daemon = ScrubDaemon(
        engine_fn=lambda: None,
        store_fn=lambda: store,
        version_fn=lambda: store.version,
        interval_s=999.0,
        wal_segments_per_cycle=64,  # rescan everything each cycle
        seed=7,
    )
    ev = daemon.step()
    if not ev.get("clean"):
        fail(f"wal: clean WAL scrubbed dirty: {ev}")

    FAULTS.arm("wal.bitrot", 1)
    cycles = step_until(daemon, KIND_WAL)
    if not daemon.repairs.get(ACTION_CHECKPOINT_REBUILD):
        fail(f"wal: no {ACTION_CHECKPOINT_REBUILD} repair: {daemon.repairs}")
    # the repair checkpoint pruned the damaged segment; a cold recovery
    # must reproduce the live store exactly from what remains on disk
    scratch = InMemoryTupleStore()
    report = recover_store(scratch, wal_dir, store.checkpoint_dir)
    if report.gap:
        fail(f"wal: post-repair recovery still sees a gap: {report.notes}")
    if scratch.version != store.version:
        fail(
            f"wal: recovered version {scratch.version} != live "
            f"{store.version}"
        )
    if set(scratch.all_tuples()) != set(store.all_tuples()):
        fail("wal: recovered tuple set differs from the live store")
    ev = daemon.step()
    if not ev.get("clean"):
        fail(f"wal: cycle after repair not clean: {ev}")
    store.close_durable()
    print(
        f"scrub gate: wal.bitrot detected in {cycles} cycle(s), "
        "checkpoint rebuilt, cold recovery byte-identical"
    )


# -- drill 3: follower skips a delta ------------------------------------------


def drill_replica(tmp: str) -> None:
    import asyncio
    import threading

    from aiohttp import web

    from keto_tpu.replication import FollowerReplicator, ReplicationSource
    from keto_tpu.store.durable import DurableTupleStore

    leader = DurableTupleStore(
        InMemoryTupleStore(), os.path.join(tmp, "lwal"), sync="always"
    )
    for i in range(5):
        leader.write_relation_tuples(t(f"n:doc{i}#view@user{i}"))

    src = ReplicationSource(leader, poll_interval_s=0.01)
    app = web.Application()
    src.register(app)
    loop = asyncio.new_event_loop()
    threading.Thread(target=loop.run_forever, daemon=True).start()

    async def _up():
        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        return runner, site._server.sockets[0].getsockname()[1]

    runner, port = asyncio.run_coroutine_threadsafe(_up(), loop).result(30)
    try:
        rep = FollowerReplicator(
            InMemoryTupleStore(),
            f"http://127.0.0.1:{port}",
            scratch_dir=os.path.join(tmp, "fscratch"),
            poll_interval_s=0.01,
        )
        rep.bootstrap()
        if rep.store.version != leader.version:
            fail("replica: bootstrap did not reach the leader version")

        # drain the WAL backlog first: the cursor starts at the head, and
        # records at or below the seeded version are version-guarded
        # no-ops that would consume the armed fault without diverging
        rep.poll_once(wait_ms=0)

        # the silent divergence: the next delta's version is applied but
        # its tuples are dropped — lag stays 0, data is wrong
        FAULTS.arm("replica.skip_delta", 1)
        leader.write_relation_tuples(t("n:doc99#view@mallory"))
        deadline = 200
        while rep.store.version < leader.version and deadline:
            rep.poll_once(wait_ms=200)
            deadline -= 1
        if rep.store.version != leader.version:
            fail("replica: follower never caught up to the leader version")
        if set(rep.store.all_tuples()) == set(leader.all_tuples()):
            fail("replica: skip_delta fault did not diverge the follower")

        daemon = ScrubDaemon(
            engine_fn=lambda: None,
            store_fn=lambda: rep.store,
            replicator_fn=lambda: rep,
            version_fn=lambda: rep.store.version,
            interval_s=999.0,
            digest_chunk_size=2,  # several chunks over a tiny store
            seed=7,
        )
        cycles = step_until(daemon, KIND_REPLICA)
        if not daemon.repairs.get(ACTION_RESEED):
            fail(f"replica: no {ACTION_RESEED} repair: {daemon.repairs}")
        # the reseed restored the leader's newest checkpoint and reset the
        # cursor; the normal tail loop replays forward to the head — this
        # time the skipped delta's tuples actually land
        deadline = 200
        while rep.store.version < leader.version and deadline:
            rep.poll_once(wait_ms=200)
            deadline -= 1
        if set(rep.store.all_tuples()) != set(leader.all_tuples()):
            fail("replica: post-reseed tuple set still differs from leader")
        if rep.store.version != leader.version:
            fail("replica: post-reseed version differs from leader")
        ev = daemon.step()
        if not ev.get("clean"):
            fail(f"replica: cycle after reseed not clean: {ev}")
    finally:
        asyncio.run_coroutine_threadsafe(runner.cleanup(), loop).result(30)
        loop.call_soon_threadsafe(loop.stop)
        leader.close_durable()
    print(
        f"scrub gate: replica.skip_delta detected in {cycles} cycle(s), "
        "follower reseeded, converged to leader"
    )


def main() -> int:
    with tempfile.TemporaryDirectory(prefix="scrub-gate-") as tmp:
        drill_device()
        drill_wal(tmp)
        drill_replica(tmp)
    print(
        json.dumps(
            {
                "scrub_gate": "ok",
                "drills": ["device_bitflip", "wal_bitrot", "replica_skip_delta"],
                "cycle_budget": CYCLE_BUDGET,
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
