#!/usr/bin/env bash
# Repo check: the gates a change must pass before review.
#
#   1. import hygiene — every keto_tpu module imports (catches moved
#      upstream APIs like the jax shard_map relocation at CI time)
#   2. sharded serving parity — tests/test_sharded_serving.py on an
#      8-way virtual CPU mesh: the edge-partitioned serving tier's
#      allowed bitsets byte-identical to the single-chip engine and the
#      host oracle, breaker fault absorption, incremental re-shard
#   3. bench smoke — bench.py --smoke end-to-end (tiny config, short
#      server leg): the serving path must boot, answer, and emit its
#      summary JSON with exit 0. Includes the attribution-leak gate:
#      the wall-clock accounting ledger (/debug/attribution) must cover
#      >= 95% of measured check wall time, else bench.py exits 3 — a
#      refactor that drops a stage's ledger marks fails here, not in
#      production. Also the encoded-wire parity gate: the id-native
#      BatchCheckEncoded leg must answer identically to the per-tuple
#      string path on both transports (encoded_parity == ok) or bench
#      exits 3
#   4. chaos soak smoke — tools/soak.py: seeded deterministic fault
#      schedule (crash/slow/nan + pool-phase drop/crash) under concurrent
#      mixed load; answer parity, snaptoken monotonicity, no lost
#      futures, bounded p99; plus the kill-and-restart drill (SIGKILL at
#      every WAL/checkpoint fault site, post-recovery parity vs a shadow
#      oracle), the device-fault drills (--device-chaos: OOM batch
#      bisection parity, compile-failure quarantine, device-loss
#      failover with bounded recovery), and the game-day election drill
#      (--election: SIGKILL the elected leader mid-traffic; a follower
#      must win the lease within 2x TTL with zero acked-write loss,
#      reads never stop, exactly one fencing-token lineage), and the
#      overload drill (--overload: offer ~10x measured capacity
#      open-loop with a criticality mix; goodput >= 0.8x capacity,
#      zero critical sheds, sheddable shed before default, retry
#      amplification <= 1.1x, brownout ladder steps back to normal)
#   5. replication gate — 1 leader + 2 followers in-process: checkpoint
#      bootstrap + WAL-tail convergence under a lag bound, token-
#      consistent reads on followers (wait AND bounce paths), read-only
#      follower write plane, replication metrics exported; plus the
#      cluster-federation drill: follower heartbeats land all 3 members
#      on the leader's /cluster/status, the leader's federated /metrics
#      (instance-labeled keto_cluster_* series) lints clean in both
#      exposition formats, and a hedged check pair stitches into ONE
#      cross-process trace on the leader's /debug/traces; ends with the
#      fast election drill: leader killed WITHOUT releasing its lease,
#      one follower self-promotes inside the bound, the demoted peer's
#      503 leader_hint is followed by the client, the loser retargets
#      its WAL tail, and the on-disk fencing lineage stays one chain
#   6. metrics lint — boot the serving stack (cluster federation on, so
#      the self-federated keto_cluster_* series are linted too), drive
#      traffic, scrape /metrics from both planes in Prometheus-text and
#      OpenMetrics formats, and fail on naming/duplicate-series/format
#      violations
#   7. reverse-index parity — the fast core of tests/test_listing.py:
#      list_objects/list_subjects answered from the transposed closure
#      D^T byte-identical to the brute-force forward-scan oracle
#      (random graphs, cycles, unicode, stale/cross-engine tokens) on
#      both query modes, plus the gather-fault breaker drill
#   8. closure microbench gate — tools/closure_microbench.py --gate:
#      incremental closure update after one edge >= 5x faster than a
#      full semiring rebuild (median-of-5 at m~2048); incremental D^T
#      maintenance >= 5x over a full re-transpose; list_objects via the
#      reverse index >= 10x over the per-candidate oracle scan
#   9. autotune gate — tools/autotune_gate.py: the in-process feedback
#      controller against a scripted ledger with a known response
#      surface: converge to the interior optimum, ride the monotone
#      knob to its bound, exercise the revert path, never apply a
#      value outside the declared bounds, freeze/thaw on a guard flip
#  10. scrub gate — tools/scrub_gate.py: every integrity fault site
#      (scrub.device_bitflip, wal.bitrot, replica.skip_delta) injected
#      against a real engine/WAL/follower, detected within the cycle
#      budget, auto-repaired, and the post-repair state byte-identical
#      to the host truth (oracle answers / cold recovery / leader set)
#  11. overload gate — tools/overload_gate.py: the overload-control
#      plane against a scripted 10x open-loop burst: goodput >= 0.8x
#      of capacity, sheds strictly sheddable-before-default and never
#      critical, accepted latency bounded by the CoDel/LIFO discipline,
#      the brownout ladder steps back to normal after the burst, every
#      transition lands in the flight recorder, and a RetryBudget caps
#      retry amplification at 1.1x under total shed
#  12. tier-1 tests — the ROADMAP.md tier-1 command, verbatim
#
# Usage: bash tools/check.sh            (from the repo root)
set -o pipefail
cd "$(dirname "$0")/.."

echo "== import hygiene =="
JAX_PLATFORMS=cpu python tools/verify_imports.py || exit 1

echo "== encoded wire parity =="
# fast-fail version of the bench encoded_parity gate: the id-native wire
# tier (vocab sync + BatchCheckEncoded on REST and gRPC) must agree with
# the per-tuple string path before anything slower runs
timeout -k 10 240 env JAX_PLATFORMS=cpu python -m pytest \
  tests/test_wire_encoded.py -q -p no:cacheprovider \
  -k "parity or resync or stale" || exit 1

echo "== sharded serving parity =="
# the sharded serving tier on an 8-way virtual CPU mesh: allowed bitsets
# must be byte-identical to the single-chip engine and the host oracle,
# the breaker must absorb injected launch faults, and append-only writes
# must re-shard incrementally
timeout -k 10 300 env JAX_PLATFORMS=cpu \
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  PALLAS_AXON_POOL_IPS= python -m pytest \
  tests/test_sharded_serving.py -q -p no:cacheprovider || exit 1

echo "== bench smoke =="
timeout -k 10 420 env JAX_PLATFORMS=cpu python bench.py --smoke || exit 1

echo "== chaos soak smoke =="
timeout -k 10 420 env JAX_PLATFORMS=cpu python tools/soak.py --smoke --seed 4 --pool --restart --device-chaos --election --overload || exit 1

echo "== replication gate =="
timeout -k 10 240 env JAX_PLATFORMS=cpu python tools/replication_gate.py || exit 1

echo "== metrics lint =="
timeout -k 10 240 env JAX_PLATFORMS=cpu python tools/lint_metrics.py || exit 1

echo "== reverse-index parity =="
# the list-serving engine suite without the server fixture: reverse-index
# answers byte-identical to the forward-scan oracle, token staleness, and
# the breaker drill — the invariants the list APIs are built on
timeout -k 10 240 env JAX_PLATFORMS=cpu python -m pytest \
  tests/test_listing.py -q -p no:cacheprovider \
  -k "not Surface" || exit 1

echo "== closure microbench gate =="
# incremental closure update after 1 edge must stay >= 5x faster than a
# full rebuild (median-of-5, m~2048), incremental D^T maintenance >= 5x
# over a full re-transpose, and list_objects through the reverse index
# >= 10x over the brute-force oracle; regressions exit non-zero here
timeout -k 10 120 python tools/closure_microbench.py --gate || exit 1

echo "== autotune gate =="
# the online autotuner's controller logic, seeded + deterministic: must
# converge, never leave the knob bounds, and exercise a revert
timeout -k 10 120 env JAX_PLATFORMS=cpu python tools/autotune_gate.py || exit 1

echo "== scrub gate =="
# the integrity plane end to end: inject each fault site, require
# detection within the cycle budget, automatic repair, and byte-identical
# post-repair state (engine vs oracle, cold recovery vs live store,
# follower vs leader)
timeout -k 10 240 env JAX_PLATFORMS=cpu python tools/scrub_gate.py || exit 1

echo "== overload gate =="
# the overload-control plane, seeded + deterministic: goodput floor at
# 10x, strict criticality shed ordering, ladder recovery, retry budget
timeout -k 10 120 env JAX_PLATFORMS=cpu python tools/overload_gate.py || exit 1

echo "== tier-1 tests =="
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
  --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly \
  2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
exit $rc
