#!/usr/bin/env python
"""Folded stacks -> standalone flamegraph (HTML with an inline SVG).

Consumes the classic folded format the sampling profiler emits
(``/debug/pprof?format=folded``, or telemetry.profiler.folded_text()):
one ``frame;frame;...;frame count`` line per unique stack. Produces a
single self-contained file — no external JS/CSS, nothing fetched — safe
to attach to a ticket or open from a support bundle.

Stdlib only, like the profiler itself: the runtime image ships no
flamegraph tooling, so this is the rendering half of the pair.

Usage:
    curl -s 'http://HOST:PORT/debug/pprof?format=folded' \
        | python tools/flame.py > flame.html
    python tools/flame.py --in stacks.folded --out flame.html
    python tools/flame.py --in stacks.folded --svg --out flame.svg
"""

from __future__ import annotations

import argparse
import sys
from html import escape

# frame-rect layout constants (SVG user units)
_ROW_H = 17
_WIDTH = 1200
_FONT = 11
_MIN_W = 0.5  # rects narrower than this are dropped (sub-pixel noise)

# muted warm palette, cycled by depth so adjacent rows read apart
_COLORS = (
    "#e5744c", "#e08a3c", "#d9a441", "#c9b24a",
    "#e06a5e", "#d98a55", "#cf9a3f", "#c27d4e",
)


def parse_folded(text: str) -> dict[tuple[str, ...], int]:
    """``stack;frames count`` lines -> {(frame, ...): count}. Lines that
    do not end in an integer are skipped (headers, blank lines)."""
    out: dict[tuple[str, ...], int] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        stack, _, count_s = line.rpartition(" ")
        if not stack:
            continue
        try:
            count = int(count_s)
        except ValueError:
            continue
        key = tuple(stack.split(";"))
        out[key] = out.get(key, 0) + count
    return out


def build_tree(folds: dict[tuple[str, ...], int]) -> dict:
    """Merge stacks into {name, value, children} (value = subtree
    samples) — the same shape /debug/pprof returns as JSON."""
    root: dict = {"name": "all", "value": 0, "children": {}}
    for stack, count in folds.items():
        root["value"] += count
        node = root
        for frame in stack:
            child = node["children"].get(frame)
            if child is None:
                child = {"name": frame, "value": 0, "children": {}}
                node["children"][frame] = child
            child["value"] += count
            node = child
    return root


def _render_rects(node: dict, x: float, depth: int, scale: float,
                  total: int, out: list[str]) -> int:
    """Emit one <g> per frame rect, children left-to-right by weight.
    Returns the deepest row used (for sizing the SVG)."""
    w = node["value"] * scale
    deepest = depth
    if depth >= 0 and w >= _MIN_W:  # depth -1 = synthetic root, not drawn
        y = depth * _ROW_H
        color = _COLORS[depth % len(_COLORS)]
        name = escape(node["name"])
        pct = 100.0 * node["value"] / total
        label = name if w > 40 else ""
        out.append(
            f'<g><title>{name} — {node["value"]} samples '
            f"({pct:.1f}%)</title>"
            f'<rect x="{x:.2f}" y="{y}" width="{w:.2f}" '
            f'height="{_ROW_H - 1}" fill="{color}" rx="1"/>'
            + (
                f'<text x="{x + 3:.2f}" y="{y + _ROW_H - 5}" '
                f'font-size="{_FONT}" font-family="monospace" '
                f'fill="#1a1a1a" clip-path="inset(0)">'
                f"{label[: max(1, int(w / 7))]}</text>"
                if label
                else ""
            )
            + "</g>"
        )
    cx = x
    for child in sorted(
        node["children"].values(), key=lambda c: -c["value"]
    ):
        cw = child["value"] * scale
        if cw < _MIN_W:
            continue
        deepest = max(
            deepest,
            _render_rects(child, cx, depth + 1, scale, total, out),
        )
        cx += cw
    return deepest


def render_svg(tree: dict) -> str:
    total = max(1, tree["value"])
    scale = _WIDTH / total
    rects: list[str] = []
    deepest = _render_rects(tree, 0.0, -1, scale, total, rects)
    height = (deepest + 1) * _ROW_H + 4
    return (
        f'<svg xmlns="http://www.w3.org/2000/svg" width="{_WIDTH}" '
        f'height="{height}" viewBox="0 0 {_WIDTH} {height}">'
        f'<rect width="{_WIDTH}" height="{height}" fill="#fdf6ec"/>'
        + "".join(rects)
        + "</svg>"
    )


def render_html(tree: dict, title: str = "keto-tpu flamegraph") -> str:
    total = tree["value"]
    return f"""<!DOCTYPE html>
<html><head><meta charset="utf-8"><title>{escape(title)}</title>
<style>
  body {{ font-family: monospace; margin: 16px; background: #fdf6ec; }}
  h1 {{ font-size: 15px; }} p {{ font-size: 12px; color: #555; }}
  svg {{ border: 1px solid #ddd; }}
</style></head>
<body>
<h1>{escape(title)}</h1>
<p>{total} samples — widths are sample shares; hover a frame for its
count. Rendered by tools/flame.py from folded stacks
(/debug/pprof?format=folded).</p>
{render_svg(tree)}
</body></html>
"""


def main() -> int:
    ap = argparse.ArgumentParser(
        description="folded stacks -> standalone flamegraph"
    )
    ap.add_argument(
        "--in", dest="infile", default="-",
        help="folded-stacks file ('-' = stdin)",
    )
    ap.add_argument(
        "--out", dest="outfile", default="-",
        help="output file ('-' = stdout)",
    )
    ap.add_argument(
        "--svg", action="store_true",
        help="emit the bare SVG instead of the HTML wrapper",
    )
    ap.add_argument("--title", default="keto-tpu flamegraph")
    args = ap.parse_args()

    text = (
        sys.stdin.read()
        if args.infile == "-"
        else open(args.infile).read()
    )
    folds = parse_folded(text)
    if not folds:
        print("no folded stacks in input", file=sys.stderr)
        return 1
    tree = build_tree(folds)
    doc = (
        render_svg(tree)
        if args.svg
        else render_html(tree, title=args.title)
    )
    if args.outfile == "-":
        sys.stdout.write(doc)
    else:
        with open(args.outfile, "w") as f:
            f.write(doc)
    return 0


if __name__ == "__main__":
    sys.exit(main())
