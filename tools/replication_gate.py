#!/usr/bin/env python
"""CI gate for the replicated read plane: 1 leader + 2 followers,
in-process, through the full Registry stack.

What must hold (the tools/check.sh tier):

- the leader (memory DSN + WAL) serves the /replication routes on its
  write plane and mints structured ``z<v>.<seg>.<off>`` ack tokens;
- both followers bootstrap from the leader's checkpoint, tail its WAL,
  and CONVERGE on every leader write within the lag bound;
- token-consistent reads work on followers in both modes: the WAIT path
  (a just-minted token answers 200 inside the freshness window) and the
  BOUNCE path (an unreachable token under a tight deadline answers 503
  with Retry-After + structured lag details);
- follower write planes reject mutations (read-only follower contract);
- replication lag/staleness metrics are exported on follower /metrics;
- the snaptoken-aware multi-endpoint client routes checks across both
  followers and returns the right answers.

Exit 0 with a one-line summary JSON on stdout; exit 1 with the
violation list otherwise.
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import tempfile
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

import httpx  # noqa: E402

from keto_tpu.driver import Config, Registry  # noqa: E402

LAG_BOUND_S = 10.0  # follower convergence bound for in-process localhost


class _Node:
    """One Registry on its own event-loop thread (HTTP is issued from
    the MAIN thread — blocking calls on a serving loop deadlock it)."""

    def __init__(self, values: dict):
        self.registry = Registry(Config(values=values))
        self.loop = asyncio.new_event_loop()
        self.thread = threading.Thread(
            target=self.loop.run_forever, daemon=True
        )
        self.thread.start()
        self.read_port, self.write_port = asyncio.run_coroutine_threadsafe(
            self.registry.start_all(), self.loop
        ).result(timeout=180)

    def stop(self) -> None:
        try:
            asyncio.run_coroutine_threadsafe(
                self.registry.stop_all(), self.loop
            ).result(timeout=30)
        finally:
            self.loop.call_soon_threadsafe(self.loop.stop)
            self.thread.join(timeout=5)


def _base(extra: dict) -> dict:
    return {
        "namespaces": [{"id": 1, "name": "n"}],
        "log": {"level": "error"},
        "engine": {"mode": "host"},
        "serve": {
            "read": {"port": 0, "host": "127.0.0.1"},
            "write": {"port": 0, "host": "127.0.0.1"},
        },
        **extra,
    }


def _params(obj: str) -> dict:
    return {
        "namespace": "n", "object": obj, "relation": "view",
        "subject_id": "alice",
    }


def main() -> int:
    t0 = time.monotonic()
    violations: list[str] = []
    root = tempfile.mkdtemp(prefix="keto-replgate-")
    nodes: list[_Node] = []
    http = httpx.Client(timeout=60)
    try:
        leader = _Node(
            _base(
                {
                    "dsn": "memory",
                    "store": {"wal": {"dir": os.path.join(root, "wal")}},
                    "replication": {"role": "leader", "poll_interval_ms": 10},
                }
            )
        )
        nodes.append(leader)

        def put(obj: str) -> None:
            r = http.put(
                f"http://127.0.0.1:{leader.write_port}/relation-tuples",
                json={
                    "namespace": "n", "object": obj, "relation": "view",
                    "subject_id": "alice",
                },
            )
            if r.status_code != 201:
                violations.append(f"leader write {obj}: {r.status_code}")

        # seed writes land in the bootstrap checkpoint; later ones only
        # reach followers over the WAL tail
        for i in range(10):
            put(f"seed{i}")
        token_seed = leader.registry.snaptoken()
        if not token_seed.startswith("z"):
            violations.append(
                f"leader minted a non-structured token: {token_seed!r}"
            )

        upstream = f"http://127.0.0.1:{leader.write_port}"
        followers = []
        for i in range(2):
            followers.append(
                _Node(
                    _base(
                        {
                            "dsn": "memory",
                            "replication": {
                                "role": "follower",
                                "upstream": upstream,
                                "dir": os.path.join(root, f"f{i}"),
                                "poll_interval_ms": 10,
                            },
                        }
                    )
                )
            )
        nodes.extend(followers)

        for i in range(10, 20):
            put(f"tail{i}")
        token_tail = leader.registry.snaptoken()

        # -- convergence under the lag bound --------------------------------
        deadline = time.monotonic() + LAG_BOUND_S
        for fi, f in enumerate(followers):
            while True:
                r = http.get(
                    f"http://127.0.0.1:{f.read_port}/check",
                    params={**_params("tail19"), "snaptoken": token_tail},
                )
                if r.status_code == 200 and r.json().get("allowed"):
                    break
                if time.monotonic() > deadline:
                    violations.append(
                        f"follower {fi} did not converge to {token_tail} "
                        f"within {LAG_BOUND_S}s (last: {r.status_code})"
                    )
                    break
                time.sleep(0.05)

        # -- WAIT path: a just-minted token answers inside the window -------
        put("fresh-write")
        token_fresh = leader.registry.snaptoken()
        for fi, f in enumerate(followers):
            r = http.get(
                f"http://127.0.0.1:{f.read_port}/check",
                params={
                    **_params("fresh-write"), "snaptoken": token_fresh,
                },
            )
            if not (r.status_code == 200 and r.json().get("allowed")):
                violations.append(
                    f"follower {fi} wait-path read failed: "
                    f"{r.status_code} {r.text[:120]}"
                )

        # -- BOUNCE path: unreachable token + tight deadline -> 503 + lag ---
        r = http.get(
            f"http://127.0.0.1:{followers[0].read_port}/check",
            params={
                **_params("fresh-write"), "snaptoken": "z99999999.0.0",
            },
            headers={"X-Request-Deadline-Ms": "50"},
        )
        if r.status_code != 503:
            violations.append(f"bounce path answered {r.status_code}")
        else:
            if "Retry-After" not in r.headers:
                violations.append("bounce response lacks Retry-After")
            details = (r.json().get("error") or {}).get("details") or {}
            if "lag_versions" not in details:
                violations.append(
                    f"bounce response lacks lag details: {r.text[:200]}"
                )

        # -- read-only follower write plane ---------------------------------
        r = http.put(
            f"http://127.0.0.1:{followers[1].write_port}/relation-tuples",
            json={
                "namespace": "n", "object": "x", "relation": "view",
                "subject_id": "alice",
            },
        )
        if r.status_code != 503 or "read-only" not in r.text:
            violations.append(
                f"follower accepted a write: {r.status_code} {r.text[:120]}"
            )

        # -- replication metrics exported -----------------------------------
        metrics = http.get(
            f"http://127.0.0.1:{followers[0].read_port}/metrics"
        ).text
        for name in (
            "keto_replication_lag_versions",
            "keto_replication_lag_seconds",
            "keto_replication_staleness_seconds",
            "keto_replication_applied_total",
        ):
            if name not in metrics:
                violations.append(f"follower /metrics lacks {name}")

        # -- snaptoken-aware multi-endpoint client across both followers ----
        from keto_tpu.client import ReplicatedRestClient

        with ReplicatedRestClient(
            [f"http://127.0.0.1:{f.read_port}" for f in followers],
            write_url=f"http://127.0.0.1:{leader.write_port}",
        ) as client:
            for _ in range(6):  # round-robins across both followers
                res = client.check(
                    "n:fresh-write#view@alice", snaptoken=token_fresh
                )
                if not res.allowed:
                    violations.append("routed client got a wrong answer")
                    break
            routed = client.router.snapshot()
            if all(v["known_version"] == 0 for v in routed.values()):
                violations.append(
                    f"router learned nothing from routed reads: {routed}"
                )

        lag_panels = [
            f.registry.replicator().lag() for f in followers
        ]
        summary = {
            "ok": not violations,
            "leader_token": token_tail,
            "followers": [
                {
                    "version": p["version"],
                    "lag_versions": p["lag_versions"],
                    "applied_total": p["applied_total"],
                }
                for p in lag_panels
            ],
            "elapsed_s": round(time.monotonic() - t0, 2),
            "violations": violations,
        }
        print(json.dumps(summary))
        if violations:
            for v in violations:
                print(f"  - {v}", file=sys.stderr)
            return 1
        return 0
    finally:
        http.close()
        for node in nodes:
            try:
                node.stop()
            except Exception as e:  # noqa: BLE001
                print(f"node stop failed: {e!r}", file=sys.stderr)
        import shutil

        shutil.rmtree(root, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
