#!/usr/bin/env python
"""CI gate for the replicated read plane: 1 leader + 2 followers,
in-process, through the full Registry stack.

What must hold (the tools/check.sh tier):

- the leader (memory DSN + WAL) serves the /replication routes on its
  write plane and mints structured ``z<v>.<seg>.<off>`` ack tokens;
- both followers bootstrap from the leader's checkpoint, tail its WAL,
  and CONVERGE on every leader write within the lag bound;
- token-consistent reads work on followers in both modes: the WAIT path
  (a just-minted token answers 200 inside the freshness window) and the
  BOUNCE path (an unreachable token under a tight deadline answers 503
  with Retry-After + structured lag details);
- follower write planes reject mutations (read-only follower contract);
- replication lag/staleness metrics are exported on follower /metrics;
- the snaptoken-aware multi-endpoint client routes checks across both
  followers and returns the right answers;
- cluster federation: both followers heartbeat to the leader, the
  leader's /cluster/status lists all 3 members alive, its /metrics
  carries instance-labeled ``keto_cluster_*`` series that pass the
  metrics linter in both exposition formats, and a hedged check pair
  renders as ONE stitched trace on the leader's /debug/traces with
  spans from at least two distinct processes.

Exit 0 with a one-line summary JSON on stdout; exit 1 with the
violation list otherwise.
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import tempfile
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

import httpx  # noqa: E402

from keto_tpu.driver import Config, Registry  # noqa: E402

LAG_BOUND_S = 10.0  # follower convergence bound for in-process localhost
CLUSTER_BOUND_S = 20.0  # heartbeat + federation-scrape settle bound
DEBUG_TOKEN = "replgate-debug"


ELECTION_BOUND_S = 15.0  # leader kill -> promoted follower bound


def _cluster(instance_id: str, election_wal: str = "") -> dict:
    doc = {
        "enabled": True,
        "instance_id": instance_id,
        "heartbeat_interval_ms": 100,
        "scrape_interval_ms": 200,
    }
    if election_wal:
        doc["election"] = {
            "enabled": True,
            "lease_ttl_s": 1.0,
            "heartbeat_interval_ms": 100,
            "wal_dir": election_wal,
        }
    return doc


class _Node:
    """One Registry on its own event-loop thread (HTTP is issued from
    the MAIN thread — blocking calls on a serving loop deadlock it)."""

    def __init__(self, values: dict):
        self.registry = Registry(Config(values=values))
        self.loop = asyncio.new_event_loop()
        self.thread = threading.Thread(
            target=self.loop.run_forever, daemon=True
        )
        self.thread.start()
        self.read_port, self.write_port = asyncio.run_coroutine_threadsafe(
            self.registry.start_all(), self.loop
        ).result(timeout=180)

    def stop(self) -> None:
        try:
            asyncio.run_coroutine_threadsafe(
                self.registry.stop_all(), self.loop
            ).result(timeout=30)
        finally:
            self.loop.call_soon_threadsafe(self.loop.stop)
            self.thread.join(timeout=5)


def _base(extra: dict) -> dict:
    return {
        "namespaces": [{"id": 1, "name": "n"}],
        "log": {"level": "error"},
        "engine": {"mode": "host"},
        "serve": {
            "read": {"port": 0, "host": "127.0.0.1"},
            "write": {"port": 0, "host": "127.0.0.1"},
        },
        **extra,
    }


def _params(obj: str) -> dict:
    return {
        "namespace": "n", "object": obj, "relation": "view",
        "subject_id": "alice",
    }


def main() -> int:
    t0 = time.monotonic()
    violations: list[str] = []
    root = tempfile.mkdtemp(prefix="keto-replgate-")
    nodes: list[_Node] = []
    http = httpx.Client(timeout=60)
    try:
        leader = _Node(
            _base(
                {
                    "dsn": "memory",
                    "store": {"wal": {"dir": os.path.join(root, "wal")}},
                    "replication": {"role": "leader", "poll_interval_ms": 10},
                    "cluster": _cluster(
                        "leader-0", election_wal=os.path.join(root, "wal")
                    ),
                    "debug": {"token": DEBUG_TOKEN},
                }
            )
        )
        nodes.append(leader)

        def put(obj: str) -> None:
            r = http.put(
                f"http://127.0.0.1:{leader.write_port}/relation-tuples",
                json={
                    "namespace": "n", "object": obj, "relation": "view",
                    "subject_id": "alice",
                },
            )
            if r.status_code != 201:
                violations.append(f"leader write {obj}: {r.status_code}")

        # seed writes land in the bootstrap checkpoint; later ones only
        # reach followers over the WAL tail
        for i in range(10):
            put(f"seed{i}")
        token_seed = leader.registry.snaptoken()
        if not token_seed.startswith("z"):
            violations.append(
                f"leader minted a non-structured token: {token_seed!r}"
            )

        upstream = f"http://127.0.0.1:{leader.write_port}"
        followers = []
        for i in range(2):
            followers.append(
                _Node(
                    _base(
                        {
                            "dsn": "memory",
                            "replication": {
                                "role": "follower",
                                "upstream": upstream,
                                "dir": os.path.join(root, f"f{i}"),
                                "poll_interval_ms": 10,
                            },
                            "cluster": _cluster(
                                f"follower-{i}",
                                election_wal=os.path.join(root, "wal"),
                            ),
                            "debug": {"token": DEBUG_TOKEN},
                        }
                    )
                )
            )
        nodes.extend(followers)

        for i in range(10, 20):
            put(f"tail{i}")
        token_tail = leader.registry.snaptoken()

        # -- convergence under the lag bound --------------------------------
        deadline = time.monotonic() + LAG_BOUND_S
        for fi, f in enumerate(followers):
            while True:
                r = http.get(
                    f"http://127.0.0.1:{f.read_port}/check",
                    params={**_params("tail19"), "snaptoken": token_tail},
                )
                if r.status_code == 200 and r.json().get("allowed"):
                    break
                if time.monotonic() > deadline:
                    violations.append(
                        f"follower {fi} did not converge to {token_tail} "
                        f"within {LAG_BOUND_S}s (last: {r.status_code})"
                    )
                    break
                time.sleep(0.05)

        # -- WAIT path: a just-minted token answers inside the window -------
        put("fresh-write")
        token_fresh = leader.registry.snaptoken()
        for fi, f in enumerate(followers):
            r = http.get(
                f"http://127.0.0.1:{f.read_port}/check",
                params={
                    **_params("fresh-write"), "snaptoken": token_fresh,
                },
            )
            if not (r.status_code == 200 and r.json().get("allowed")):
                violations.append(
                    f"follower {fi} wait-path read failed: "
                    f"{r.status_code} {r.text[:120]}"
                )

        # -- BOUNCE path: unreachable token + tight deadline -> 503 + lag ---
        r = http.get(
            f"http://127.0.0.1:{followers[0].read_port}/check",
            params={
                **_params("fresh-write"), "snaptoken": "z99999999.0.0",
            },
            headers={"X-Request-Deadline-Ms": "50"},
        )
        if r.status_code != 503:
            violations.append(f"bounce path answered {r.status_code}")
        else:
            if "Retry-After" not in r.headers:
                violations.append("bounce response lacks Retry-After")
            details = (r.json().get("error") or {}).get("details") or {}
            if "lag_versions" not in details:
                violations.append(
                    f"bounce response lacks lag details: {r.text[:200]}"
                )

        # -- read-only follower write plane ---------------------------------
        r = http.put(
            f"http://127.0.0.1:{followers[1].write_port}/relation-tuples",
            json={
                "namespace": "n", "object": "x", "relation": "view",
                "subject_id": "alice",
            },
        )
        if r.status_code != 503 or "read-only" not in r.text:
            violations.append(
                f"follower accepted a write: {r.status_code} {r.text[:120]}"
            )

        # -- replication metrics exported -----------------------------------
        metrics = http.get(
            f"http://127.0.0.1:{followers[0].read_port}/metrics"
        ).text
        for name in (
            "keto_replication_lag_versions",
            "keto_replication_lag_seconds",
            "keto_replication_staleness_seconds",
            "keto_replication_applied_total",
        ):
            if name not in metrics:
                violations.append(f"follower /metrics lacks {name}")

        # -- snaptoken-aware multi-endpoint client across both followers ----
        from keto_tpu.client import ReplicatedRestClient

        with ReplicatedRestClient(
            [f"http://127.0.0.1:{f.read_port}" for f in followers],
            write_url=f"http://127.0.0.1:{leader.write_port}",
        ) as client:
            for _ in range(6):  # round-robins across both followers
                res = client.check(
                    "n:fresh-write#view@alice", snaptoken=token_fresh
                )
                if not res.allowed:
                    violations.append("routed client got a wrong answer")
                    break
            routed = client.router.snapshot()
            if all(v["known_version"] == 0 for v in routed.values()):
                violations.append(
                    f"router learned nothing from routed reads: {routed}"
                )

        # -- cluster federation: all 3 members on the leader's status -------
        deadline = time.monotonic() + CLUSTER_BOUND_S
        status: dict = {}
        while True:
            r = http.get(
                f"http://127.0.0.1:{leader.read_port}/cluster/status"
            )
            status = r.json() if r.status_code == 200 else {}
            rollup = status.get("cluster") or {}
            if rollup.get("alive", 0) >= 3 and rollup.get(
                "health"
            ) not in (None, "unknown"):
                break
            if time.monotonic() > deadline:
                violations.append(
                    "cluster did not reach 3 alive federated members "
                    f"within {CLUSTER_BOUND_S}s: "
                    f"{json.dumps(status)[:300]}"
                )
                break
            time.sleep(0.1)
        member_ids = {
            m.get("instance_id") for m in status.get("members", [])
        }
        for want in ("leader-0", "follower-0", "follower-1"):
            if want not in member_ids:
                violations.append(
                    f"/cluster/status lacks member {want}: {member_ids}"
                )

        # -- federated metrics: instance-labeled gauges, lint-clean --------
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from lint_metrics import lint_text

        for om in (False, True):
            fmt = "openmetrics" if om else "text"
            r = http.get(
                f"http://127.0.0.1:{leader.read_port}/metrics",
                headers=(
                    {"Accept": "application/openmetrics-text"} if om else {}
                ),
            )
            problems = lint_text(r.text, openmetrics=om)
            if problems:
                violations.append(
                    f"leader federated /metrics ({fmt}) fails lint: "
                    f"{problems[:3]}"
                )
            for inst in ("follower-0", "follower-1"):
                want = (
                    "keto_cluster_replication_lag_versions"
                    f'{{instance="{inst}"}}'
                )
                if want not in r.text:
                    violations.append(
                        f"leader /metrics ({fmt}) lacks {want}"
                    )

        # -- stitched hedged trace: one trace id, spans from 2 processes ---
        from keto_tpu.client import ReplicatedRestClient as _RC
        from keto_tpu.client.hedge import HedgePolicy, Hedger

        hedger = Hedger(HedgePolicy(delay_s=0.0))  # always hedge
        stitched = None
        try:
            with _RC(
                [f"http://127.0.0.1:{f.read_port}" for f in followers],
                write_url=f"http://127.0.0.1:{leader.write_port}",
                hedger=hedger,
            ) as rc:
                deadline = time.monotonic() + CLUSTER_BOUND_S
                while stitched is None and time.monotonic() < deadline:
                    res = rc.check(
                        "n:fresh-write#view@alice", snaptoken=token_fresh
                    )
                    tid = res.traceparent.split("-")[1]
                    # the losing attempt's span rings slightly later
                    for _ in range(20):
                        r = http.get(
                            f"http://127.0.0.1:{leader.read_port}"
                            "/debug/traces",
                            params={"trace_id": tid},
                            headers={"X-Debug-Token": DEBUG_TOKEN},
                        )
                        doc = r.json() if r.status_code == 200 else {}
                        insts = {
                            s.get("instance")
                            for s in doc.get("spans", [])
                        }
                        if doc.get("stitched") and len(insts) >= 2:
                            stitched = doc
                            break
                        time.sleep(0.1)
        finally:
            hedger.close()
        if stitched is None:
            violations.append(
                "no stitched hedged trace with spans from >=2 instances "
                f"within {CLUSTER_BOUND_S}s"
            )
        else:
            hedge = stitched.get("hedge") or {}
            if not hedge.get("winner"):
                violations.append(
                    f"stitched trace names no winner: {hedge}"
                )
            if not stitched.get("timeline"):
                violations.append("stitched trace has an empty timeline")

        lag_panels = [
            f.registry.replicator().lag() for f in followers
        ]

        # -- automated failover: kill the leader, the fleet self-drives -----
        # The leader dies WITHOUT releasing its lease (crash semantics):
        # the survivors must notice the TTL lapse, elect by replication
        # position, replay the shared WAL tail, and open their write
        # plane — all while reads keep answering.
        em = leader.registry._election
        if em is None:
            violations.append("election: leader built no ElectionManager")
        else:
            em.stop(release=False)
            leader.registry._election = None
        leader.stop()
        nodes.remove(leader)
        t_kill = time.monotonic()

        reads_ok, reads_bad = 0, 0
        winner = loser = None
        deadline = time.monotonic() + ELECTION_BOUND_S
        while time.monotonic() < deadline:
            for f in followers:
                r = http.get(
                    f"http://127.0.0.1:{f.read_port}/check",
                    params=_params("tail19"),
                )
                if r.status_code == 200:
                    reads_ok += 1
                else:
                    reads_bad += 1
            promoted = [
                f for f in followers
                if f.registry._election is not None
                and f.registry._election.role == "leader"
            ]
            if len(promoted) == 1:
                winner = promoted[0]
                loser = next(f for f in followers if f is not winner)
                break
            time.sleep(0.1)
        failover_s = time.monotonic() - t_kill
        if reads_bad:
            violations.append(
                f"election: {reads_bad} reads failed during failover "
                f"({reads_ok} ok) — reads must never stop"
            )
        if winner is None:
            violations.append(
                "election: no follower promoted within "
                f"{ELECTION_BOUND_S}s"
            )
        else:
            new_write = f"http://127.0.0.1:{winner.write_port}"

            # the winner's own /cluster/status names it leader with a
            # bumped term (satellite: election state on the status doc)
            r = http.get(
                f"http://127.0.0.1:{winner.read_port}/cluster/status"
            )
            edoc = {}
            if r.status_code == 200:
                edoc = (r.json().get("cluster") or {}).get("election") or {}
            if edoc.get("role") != "leader" or edoc.get("term", 0) < 2:
                violations.append(
                    f"election: winner /cluster/status election doc is "
                    f"{edoc!r}, want role=leader term>=2"
                )

            # the promoted write plane opens: a direct write answers 201
            r = http.put(
                f"{new_write}/relation-tuples",
                json={
                    "namespace": "n", "object": "post-failover",
                    "relation": "view", "subject_id": "alice",
                },
            )
            if r.status_code != 201:
                violations.append(
                    f"election: promoted write plane answered "
                    f"{r.status_code}: {r.text[:120]}"
                )

            # the demoted peer still refuses writes — but its 503 now
            # carries the new leader's coordinates, and the client
            # follows them without operator help
            r = http.put(
                f"http://127.0.0.1:{loser.write_port}/relation-tuples",
                json={
                    "namespace": "n", "object": "misrouted",
                    "relation": "view", "subject_id": "alice",
                },
            )
            hint = {}
            if r.status_code == 503:
                hint = (
                    (r.json().get("error") or {}).get("details") or {}
                ).get("leader_hint") or {}
            if hint.get("write_url") != new_write:
                violations.append(
                    f"election: loser 503 leader_hint {hint!r} does not "
                    f"point at {new_write}"
                )
            from keto_tpu.client import ReplicatedRestClient as _RC2

            with _RC2(
                [f"http://127.0.0.1:{f.read_port}" for f in followers],
                write_url=f"http://127.0.0.1:{loser.write_port}",
            ) as rc:
                try:
                    rc.create_relation_tuple(
                        "n:follow-the-hint#view@alice"
                    )
                except Exception as e:  # noqa: BLE001
                    violations.append(
                        f"election: client did not follow leader_hint: "
                        f"{e!r}"
                    )

            # the loser retargeted its tail at the winner and converges
            # on post-failover writes with no re-bootstrap
            up = loser.registry.replicator().upstream.rstrip("/")
            if up != new_write:
                violations.append(
                    f"election: loser still tails {up}, not {new_write}"
                )
            deadline = time.monotonic() + LAG_BOUND_S
            converged = False
            while time.monotonic() < deadline and not converged:
                r = http.get(
                    f"http://127.0.0.1:{loser.read_port}/check",
                    params=_params("follow-the-hint"),
                )
                converged = (
                    r.status_code == 200 and r.json().get("allowed")
                )
                if not converged:
                    time.sleep(0.05)
            if not converged:
                violations.append(
                    "election: post-failover write never reached the "
                    f"retargeted loser within {LAG_BOUND_S}s"
                )

        # exactly one strictly-increasing fencing-token lineage on disk
        from keto_tpu.cluster.election import LeaseStore

        lineage = LeaseStore(os.path.join(root, "wal")).lineage()
        terms = [rec["term"] for rec in lineage]
        if len(terms) < 2 or any(
            b - a != 1 for a, b in zip(terms, terms[1:])
        ):
            violations.append(
                f"election: fencing lineage is not one chain: {terms}"
            )

        summary = {
            "ok": not violations,
            "leader_token": token_tail,
            "followers": [
                {
                    "version": p["version"],
                    "lag_versions": p["lag_versions"],
                    "applied_total": p["applied_total"],
                }
                for p in lag_panels
            ],
            "cluster_alive": (status.get("cluster") or {}).get("alive"),
            "cluster_health": (status.get("cluster") or {}).get("health"),
            "stitched_instances": sorted(
                (stitched or {}).get("instances") or []
            ),
            "election": {
                "failover_s": round(failover_s, 2),
                "winner": (
                    winner.registry._election.instance_id
                    if winner is not None
                    and winner.registry._election is not None
                    else None
                ),
                "lineage_terms": terms,
                "reads_during_failover": reads_ok,
            },
            "elapsed_s": round(time.monotonic() - t0, 2),
            "violations": violations,
        }
        print(json.dumps(summary))
        if violations:
            for v in violations:
                print(f"  - {v}", file=sys.stderr)
            return 1
        return 0
    finally:
        http.close()
        for node in nodes:
            try:
                node.stop()
            except Exception as e:  # noqa: BLE001
                print(f"node stop failed: {e!r}", file=sys.stderr)
        import shutil

        shutil.rmtree(root, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
