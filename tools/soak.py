#!/usr/bin/env python
"""Seeded chaos soak: deterministic fault schedule under concurrent load.

Drives the full serving stack (registry -> breaker-wrapped device engine ->
batcher) with a mixed read-write workload while a seeded schedule arms
crash / slowness / garbage-output faults at fixed OPERATION COUNTS (not
wall-clock), so the same seed always injects the same faults at the same
points in the workload. Thread interleavings still vary run to run; every
invariant below is interleaving-independent:

- **Answer parity.** Tuples inserted before the soak and never touched
  must always check True; tuples never inserted must always check False;
  a tuple the writer has durably committed (insert-only set) must never
  check False when read at-least-as-fresh (min_version pinned to its
  commit). Transient TYPED errors (shed, crashed dispatcher, deadline)
  are tolerated during fault windows — wrong ANSWERS never are.
- **Snaptoken monotonicity.** The read-plane snaptoken never regresses.
- **No lost or double-resolved futures.** Every check resolves (answer or
  typed error) inside its per-op timeout — a lost future would surface as
  a timeout, a double-resolution as a decode-stage crash. Both count
  against the run. The pipeline must also drain to zero at the end.
- **Bounded tail latency.** p99 across the run (fault windows included)
  stays under a generous budget — a wedged stage or an unculled stuck
  batch blows it immediately.

A final parity sweep (faults cleared) compares every asserted tuple
against a fresh host oracle over the final store.

The optional pool phase (``--pool``) forks a 3-worker SO_REUSEPORT
replica pool and mixes the distribution faults the single process cannot
express — ``delta.drop`` (silent version gap -> resync handshake),
``delta.slow`` (stalled propagation), ``replica.crash`` (supervisor
respawn) — asserting every committed write converges to 200 on fresh
connections afterward.

Usage:
    python tools/soak.py --smoke --seed 4        # the tools/check.sh tier
    python tools/soak.py --seed 7 --ops 20000    # longer soak
    python tools/soak.py --smoke --pool          # include the fork phase

Exit 0 and a one-line summary JSON on stdout when every invariant holds;
exit 1 with the violation list otherwise.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import sys
import threading
import time
from concurrent.futures import TimeoutError as _FutTimeout

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def _restart_child_main(spec_raw: str) -> int:
    """Inner process of the kill-and-restart drill (--restart): recover
    the durable store, run a deterministic single-writer op stream
    printing an INTENT line before each mutator and an ACK line after it
    returns, and — when the spec arms a crash-fault site — SIGKILL
    ourselves the moment it fires. The parent (run_restart_drill) replays
    the ack protocol against its own read-only recovery of the directory.

    Imports stay store-local on purpose: a dozen child processes that each
    paid the jax import tax would blow the soak's CI budget.
    """
    import signal

    spec = json.loads(spec_raw)
    from keto_tpu.faults import FAULTS, FaultInjected
    from keto_tpu.relationtuple.definitions import (
        RelationTuple,
        SubjectID,
    )
    from keto_tpu.store import (
        ColumnarTupleStore,
        DurableTupleStore,
        InMemoryTupleStore,
    )
    from keto_tpu.store.wal import encode_tuple

    def emit(obj) -> None:
        print(json.dumps(obj), flush=True)

    FAULTS.reset()
    inner = (
        InMemoryTupleStore()
        if spec["kind"] == "memory"
        else ColumnarTupleStore()
    )
    store = DurableTupleStore(
        inner,
        spec["dir"],
        sync="always",
        # the drill drives checkpoints explicitly; background triggers
        # would make the replay accounting nondeterministic
        checkpoint_interval_versions=10**9,
        checkpoint_interval_s=0.0,
    )
    rep = store.recovery
    emit(
        {
            "recovered": True,
            "version": rep.final_version,
            "replayed": rep.replayed_deltas,
            "gap": rep.gap,
            "checkpoint_version": rep.checkpoint_version,
        }
    )
    site = spec.get("site")
    fault_at = spec.get("fault_at")
    ops = int(spec["ops"])
    rng = random.Random(int(spec["seed"]) * 7919 + int(spec["cycle"]))
    candidates = list(inner.all_tuples())
    try:
        for i in range(ops):
            if i == fault_at and site == "checkpoint.crash_mid_write":
                FAULTS.arm(site)
                emit({"ckpt_at": i})
                store.checkpoint_now()  # raises FaultInjected
            if i == fault_at and site in (
                "wal.torn_write", "wal.corrupt_crc", "wal.crash_after_append"
            ):
                FAULTS.arm(site)
            if candidates and rng.random() < 0.18:
                t = candidates[rng.randrange(len(candidates))]
                emit({"op": i, "k": "d", "t": encode_tuple(t)})
                store.delete_relation_tuples(t)
                candidates.remove(t)
            else:
                t = RelationTuple(
                    namespace="n",
                    object=f"o{rng.randrange(max(8, ops * 3))}",
                    relation="view",
                    subject=SubjectID(id=f"u{rng.randrange(7)}"),
                )
                emit({"op": i, "k": "w", "t": encode_tuple(t)})
                store.write_relation_tuples(t)
                if t not in candidates:
                    candidates.append(t)
            emit({"ack": i, "version": store.version})
            if site is None and i == ops // 2:
                store.checkpoint_now()
                emit({"ckpt": i, "version": store.version})
    except FaultInjected as e:
        # a real crash, not an orderly unwind: nothing may flush or close
        emit({"crashed": True, "site": e.site})
        os.kill(os.getpid(), signal.SIGKILL)
    if site is None:
        store.close_durable()  # exercises the shutdown checkpoint
    emit({"done": True, "version": store.version})
    return 0


def _promotion_child_main(spec_raw: str) -> int:
    """Leader process of the SIGKILL-promotion drill (--restart): a
    durable store plus the real replication routes (ReplicationSource on
    a bare aiohttp app — no engine stack, same import-tax discipline as
    the restart child). Protocol: write a prefix, cut a checkpoint (the
    follower must seed from a real checkpoint), print ``ready`` with the
    port, wait for ``go`` on stdin (the parent's follower has
    bootstrapped), then stream single-writer ops with INTENT/ACK lines —
    and SIGKILL ourselves right after the ``kill_at`` write lands
    durably but BEFORE its ack, the durable-but-unacked edge promotion
    must surface."""
    import asyncio
    import signal

    from aiohttp import web

    spec = json.loads(spec_raw)
    from keto_tpu.relationtuple.definitions import (
        RelationTuple,
        SubjectID,
    )
    from keto_tpu.replication.leader import ReplicationSource
    from keto_tpu.store import DurableTupleStore, InMemoryTupleStore
    from keto_tpu.store.wal import encode_tuple

    def emit(obj) -> None:
        print(json.dumps(obj), flush=True)

    store = DurableTupleStore(
        InMemoryTupleStore(),
        spec["dir"],
        sync="always",  # WAL-before-ack: the invariant under test
        checkpoint_interval_versions=10**9,
        checkpoint_interval_s=0.0,
    )
    rng = random.Random(int(spec["seed"]) * 104729)
    ops = int(spec["ops"])
    kill_at = int(spec["kill_at"])

    def write_op(i: int) -> None:
        t = RelationTuple(
            namespace="n", object=f"promo{i}", relation="view",
            subject=SubjectID(id=f"u{rng.randrange(5)}"),
        )
        emit({"op": i, "t": encode_tuple(t)})
        store.write_relation_tuples(t)

    prefix = max(1, ops // 3)
    for i in range(prefix):
        write_op(i)
        emit(
            {
                "ack": i,
                "version": store.version,
                "token": str(store.current_token()),
            }
        )
    store.checkpoint_now()

    src = ReplicationSource(store, poll_interval_s=0.01)
    app = web.Application()
    src.register(app)
    loop = asyncio.new_event_loop()
    threading.Thread(target=loop.run_forever, daemon=True).start()

    async def _serve() -> int:
        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        return site._server.sockets[0].getsockname()[1]

    port = asyncio.run_coroutine_threadsafe(_serve(), loop).result(
        timeout=60
    )
    emit({"ready": True, "port": port, "version": store.version})
    sys.stdin.readline()  # parent's follower has seeded: start streaming

    for i in range(prefix, ops):
        write_op(i)
        if i == kill_at:
            # the frame is on disk (sync=always) but the ack never
            # leaves: recovery surfacing exactly this op is correct
            os.kill(os.getpid(), signal.SIGKILL)
        emit(
            {
                "ack": i,
                "version": store.version,
                "token": str(store.current_token()),
            }
        )
        time.sleep(0.01)  # let the follower tail live traffic
    emit({"done": True})
    return 0


def _election_child_main(spec_raw: str) -> int:
    """Elected-leader process of the game-day drill (``--election``): a
    durable store, the real replication routes, AND a real
    :class:`ElectionManager` holding the fencing-token lease over the
    shared WAL directory. Every streamed write is gated on
    ``is_writable()`` — the per-mutation fence the write plane uses —
    and acked only after its WAL frame is durable. SIGKILLs itself right
    after the ``kill_at`` write lands durably but BEFORE its ack, with
    the lease deliberately un-released: the survivors must wait out the
    TTL, exactly like a real power-cord failover."""
    import asyncio
    import signal

    from aiohttp import web

    spec = json.loads(spec_raw)
    from keto_tpu.cluster.election import ElectionManager, LeaseStore
    from keto_tpu.relationtuple.definitions import (
        RelationTuple,
        SubjectID,
    )
    from keto_tpu.replication.leader import ReplicationSource
    from keto_tpu.store import DurableTupleStore, InMemoryTupleStore
    from keto_tpu.store.wal import encode_tuple

    def emit(obj) -> None:
        print(json.dumps(obj), flush=True)

    store = DurableTupleStore(
        InMemoryTupleStore(),
        spec["dir"],
        sync="always",  # WAL-before-ack: the zero-loss invariant
        checkpoint_interval_versions=10**9,
        checkpoint_interval_s=0.0,
    )
    rng = random.Random(int(spec["seed"]) * 7919)
    ops = int(spec["ops"])
    kill_at = int(spec["kill_at"])

    def write_op(i: int) -> None:
        t = RelationTuple(
            namespace="n", object=f"gameday{i}", relation="view",
            subject=SubjectID(id=f"u{rng.randrange(5)}"),
        )
        emit({"op": i, "t": encode_tuple(t)})
        store.write_relation_tuples(t)

    prefix = max(1, ops // 3)
    for i in range(prefix):
        write_op(i)
        emit({"ack": i, "version": store.version})
    store.checkpoint_now()

    src = ReplicationSource(store, poll_interval_s=0.01)
    app = web.Application()
    src.register(app)
    loop = asyncio.new_event_loop()
    threading.Thread(target=loop.run_forever, daemon=True).start()

    async def _serve() -> int:
        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        return site._server.sockets[0].getsockname()[1]

    port = asyncio.run_coroutine_threadsafe(_serve(), loop).result(
        timeout=60
    )
    em = ElectionManager(
        LeaseStore(spec["dir"]),
        instance_id="gameday-leader",
        lease_ttl_s=float(spec["ttl"]),
        heartbeat_interval_s=float(spec["hb"]),
        write_url=f"http://127.0.0.1:{port}",
    )
    if not em.ensure_leadership():
        emit({"error": "leader could not take the bootstrap lease"})
        return 1
    em.start()  # renews every hb; SIGKILL leaves the lease to expire
    emit({"ready": True, "port": port, "version": store.version,
          "term": em.term})
    sys.stdin.readline()  # followers seeded: start live traffic

    for i in range(prefix, ops):
        if not em.is_writable():
            emit({"fenced": i})
            break
        write_op(i)
        if i == kill_at:
            # durable but unacked, lease un-released: the real crash
            os.kill(os.getpid(), signal.SIGKILL)
        emit({"ack": i, "version": store.version})
        time.sleep(0.01)
    emit({"done": True})
    return 0


if "--restart-child" in sys.argv:
    # handled BEFORE the keto_tpu.driver import below: the child only
    # needs the store layer, not the engine stack
    sys.exit(
        _restart_child_main(sys.argv[sys.argv.index("--restart-child") + 1])
    )

if "--promotion-child" in sys.argv:
    sys.exit(
        _promotion_child_main(
            sys.argv[sys.argv.index("--promotion-child") + 1]
        )
    )

if "--election-child" in sys.argv:
    sys.exit(
        _election_child_main(
            sys.argv[sys.argv.index("--election-child") + 1]
        )
    )

from keto_tpu.driver import Config, Registry  # noqa: E402
from keto_tpu.faults import FAULTS  # noqa: E402
from keto_tpu.relationtuple.definitions import (  # noqa: E402
    RelationTuple,
    SubjectID,
)
from keto_tpu.utils.errors import KetoError  # noqa: E402

PER_OP_TIMEOUT_S = 10.0  # lost-future detector: no answer in this long
P99_BUDGET_S = 3.0  # generous; catches wedged stages, not CI jitter

#: the schedule draws from these (kind, site, arm kwargs). Slow sleeps are
#: kept far below PER_OP_TIMEOUT_S so a slept batch still resolves.
FAULT_MENU = (
    ("crash", "batcher.dispatcher_die", {}),
    ("crash", "device.compile_error", {"times": 2}),
    ("nan", "device.batch_nan", {}),
    ("slow", "device.slow", {"sleep_ms": 40, "times": 3}),
    ("slow", "batcher.dispatch_slow", {"sleep_ms": 25, "times": 3}),
)


def _tup(obj: str) -> RelationTuple:
    return RelationTuple(
        namespace="n", object=obj, relation="view",
        subject=SubjectID(id="alice"),
    )


class _Violations:
    def __init__(self):
        self.items: list[str] = []
        self._lock = threading.Lock()

    def add(self, msg: str) -> None:
        with self._lock:
            if len(self.items) < 50:  # bounded: one bad invariant can spam
                self.items.append(msg)


def _percentile(sorted_vals: list[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    return sorted_vals[min(len(sorted_vals) - 1, int(q * len(sorted_vals)))]


def run_engine_soak(
    seed: int,
    n_ops: int = 1200,
    n_readers: int = 4,
    n_writes: int = 120,
    n_faults: int = 6,
) -> dict:
    """The in-process phase: registry + breaker + batcher under load.
    Returns the summary dict; violations are listed under 'violations'."""
    rng = random.Random(seed)
    FAULTS.reset()
    cfg = Config(
        values={
            "namespaces": [{"id": 1, "name": "n"}],
            "log": {"level": "error"},
            "engine": {
                "mode": "device",
                "max_batch": 256,
                "cache_size": 0,  # a cache hit would mask engine faults
                "encoded_cache_size": 0,
                "fallback_threshold": 3,
                "fallback_cooldown_ms": 100,
            },
        }
    )
    reg = Registry(cfg)
    store = reg.store()

    static_true = [f"static{i}" for i in range(32)]
    static_false = [f"ghost{i}" for i in range(32)]
    store.transact_relation_tuples([_tup(o) for o in static_true], [])
    checker = reg.checker()

    # committed insert-only tuples: (object, min_version at/after commit)
    committed: list[tuple[str, int]] = []
    committed_lock = threading.Lock()
    violations = _Violations()
    ops_done = [0] * n_readers
    latencies: list[list[tuple[float, bool]]] = [[] for _ in range(n_readers)]
    tolerated: dict[str, int] = {}
    tol_lock = threading.Lock()
    timeouts = [0]
    stop = threading.Event()
    fault_window = threading.Event()  # any injected fault still pending

    # -- deterministic schedule: (trigger at total-op count, menu entry) ----
    schedule = sorted(
        (rng.randrange(n_ops // 8, n_ops), rng.choice(FAULT_MENU))
        for _ in range(n_faults)
    )
    injected: list[dict] = []

    def injector():
        pending = list(schedule)
        armed_sites: list[str] = []
        while not stop.is_set():
            total = sum(ops_done)
            while pending and pending[0][0] <= total:
                trigger, (kind, site, kw) = pending.pop(0)
                if kind == "slow":
                    FAULTS.arm_slow(site, **kw)
                else:
                    FAULTS.arm(site, **kw)
                armed_sites.append(site)
                injected.append(
                    {"at_op": trigger, "kind": kind, "site": site}
                )
                fault_window.set()
            if fault_window.is_set() and not any(
                FAULTS.armed(s) or FAULTS.slow_armed(s)
                for s in armed_sites
            ):
                fault_window.clear()  # everything injected was consumed
            if not pending and not fault_window.is_set():
                return
            stop.wait(0.002)

    def writer():
        wrote = 0
        while wrote < n_writes and not stop.is_set():
            obj = f"dyn{wrote}"
            churn = f"churn{wrote % 8}"
            before = store.version
            # churn tuples cycle insert/delete for version traffic; their
            # answers are never asserted. dyn tuples are insert-only, so
            # "committed => never False" holds at any later version.
            if wrote % 3 == 2:
                store.transact_relation_tuples([], [_tup(churn)])
            else:
                store.transact_relation_tuples(
                    [_tup(obj), _tup(churn)], []
                )
                with committed_lock:
                    committed.append((obj, store.version))
            if store.version <= before:
                violations.add(
                    f"store version did not advance: {before} -> "
                    f"{store.version}"
                )
            wrote += 1
            time.sleep(0.001)

    def classify(e: BaseException) -> None:
        name = type(e).__name__
        with tol_lock:
            tolerated[name] = tolerated.get(name, 0) + 1

    def reader(idx: int):
        r = random.Random(seed * 1000 + idx)
        my_ops = n_ops // n_readers
        for _ in range(my_ops):
            if stop.is_set():
                return
            roll = r.random()
            min_version = 0
            if roll < 0.4:
                obj, want = r.choice(static_true), True
            elif roll < 0.7:
                obj, want = r.choice(static_false), False
            else:
                with committed_lock:
                    if committed:
                        obj, min_version = r.choice(committed)
                        want = True
                    else:
                        obj, want = r.choice(static_true), True
            in_window = fault_window.is_set()
            t0 = time.perf_counter()
            try:
                got = checker.check(
                    _tup(obj),
                    timeout=PER_OP_TIMEOUT_S,
                    min_version=min_version,
                )
            except _FutTimeout:
                timeouts[0] += 1  # a lost future surfaces exactly here
            except KetoError as e:
                classify(e)  # typed + transient: tolerated, not correct-
                # ness — wrong answers below are the real violations
            except Exception as e:  # noqa: BLE001
                violations.add(f"untyped error from check: {e!r}")
            else:
                if got is not want:
                    violations.add(
                        f"wrong answer for {obj}: got {got}, want {want}"
                        f" (min_version={min_version})"
                    )
            latencies[idx].append((time.perf_counter() - t0, in_window))
            ops_done[idx] += 1

    def snaptoken_monitor():
        last = -1
        while not stop.is_set():
            v = int(reg.read_snaptoken())
            if v < last:
                violations.add(f"snaptoken regressed: {last} -> {v}")
            last = v
            stop.wait(0.005)

    threads = [
        threading.Thread(target=reader, args=(i,), daemon=True)
        for i in range(n_readers)
    ]
    threads += [
        threading.Thread(target=writer, daemon=True),
        threading.Thread(target=snaptoken_monitor, daemon=True),
    ]
    inj = threading.Thread(target=injector, daemon=True)
    t_start = time.perf_counter()
    for t in threads:
        t.start()
    inj.start()
    for t in threads[:n_readers]:
        t.join(timeout=300)
        if t.is_alive():
            violations.add("reader wedged past the soak budget")
    stop.set()
    inj.join(timeout=10)
    for t in threads[n_readers:]:
        t.join(timeout=10)
    wall_s = time.perf_counter() - t_start

    # -- drain + final parity sweep against the host oracle -----------------
    FAULTS.reset()  # disarm leftovers (e.g. an unconsumed slow arming)
    deadline = time.time() + 30
    stats = checker.pipeline_stats()
    while stats["batches_in_pipeline"] and time.time() < deadline:
        time.sleep(0.05)
        stats = checker.pipeline_stats()
    if stats["batches_in_pipeline"]:
        violations.add(
            f"pipeline did not drain: {stats['batches_in_pipeline']} "
            "batches still registered"
        )
    from keto_tpu.engine.check import CheckEngine

    oracle = CheckEngine(store, max_depth=5)
    with committed_lock:
        sweep = (
            [(o, True) for o in static_true]
            + [(o, False) for o in static_false]
            + [(o, True) for o, _v in committed]
        )
    parity_mismatches = 0
    for obj, want in sweep:
        o = oracle.subject_is_allowed(_tup(obj))
        try:
            c = checker.check(_tup(obj), timeout=PER_OP_TIMEOUT_S)
        except KetoError:
            c = None  # breaker may still be cooling down; oracle is truth
        if o is not want or (c is not None and c is not o):
            parity_mismatches += 1
            violations.add(
                f"parity sweep: {obj} oracle={o} served={c} want={want}"
            )

    all_lat = sorted(l for per in latencies for (l, _w) in per)
    window_lat = sorted(l for per in latencies for (l, w) in per if w)
    p99 = _percentile(all_lat, 0.99)
    if p99 > P99_BUDGET_S:
        violations.add(f"p99 {p99 * 1e3:.0f}ms over {P99_BUDGET_S}s budget")
    if timeouts[0]:
        violations.add(f"{timeouts[0]} checks timed out (lost futures?)")

    checker.close()
    summary = {
        "phase": "engine",
        "seed": seed,
        "ops": sum(ops_done),
        "wall_s": round(wall_s, 2),
        "faults_injected": injected,
        "tolerated_errors": tolerated,
        "timeouts": timeouts[0],
        "p50_ms": round(_percentile(all_lat, 0.50) * 1e3, 2),
        "p99_ms": round(p99 * 1e3, 2),
        "p99_fault_window_ms": round(
            _percentile(window_lat, 0.99) * 1e3, 2
        ),
        "deadline_culls": stats.get("deadline_expired", {}),
        "parity_mismatches": parity_mismatches,
        "violations": violations.items,
    }
    return summary


def run_overload_drill(seed: int, smoke: bool = True) -> dict:
    """The 10x open-loop overload drill (--overload): boot the serving
    stack with the overload-control plane on, measure this process's
    closed-loop check capacity, then offer ~10x that rate open-loop with
    a 20/60/20 critical/default/sheddable mix, every shed retried
    through a shared client RetryBudget. Invariants:

    - goodput (served accepted checks/s) during the burst >= 0.8x the
      measured capacity — admission control keeps the engine busy on
      work it finishes instead of queueing everything;
    - zero critical-class sheds; the first default-class shed never
      precedes the first sheddable-class shed;
    - accepted checks keep a bounded p99 (the CoDel cull + LIFO flip
      serve admitted work fresh);
    - client retry amplification (attempts/requests) <= 1.1x;
    - the brownout ladder is visibly engaged during the burst (state >=
      shed_sheddable, flight kind=overload transitions recorded) and
      steps back to normal within the hysteresis windows after the
      offered load drops to 1x.
    """
    from concurrent.futures import ThreadPoolExecutor

    from keto_tpu.client.retry import RetryBudget
    from keto_tpu.engine.overload import CRITICAL, DEFAULT, SHEDDABLE
    from keto_tpu.utils.errors import ErrResourceExhausted

    FAULTS.reset()
    hysteresis_s = 0.4
    cfg = Config(
        values={
            "namespaces": [{"id": 1, "name": "n"}],
            "log": {"level": "error"},
            "engine": {
                "mode": "device",
                # a deliberately window-bound engine (~max_batch/window
                # checks/s) so a thread-pool client can genuinely offer
                # 10x its capacity — the pool of blocking workers tops
                # out near 4k submits/s on a small box, so the engine
                # must serve well under that for real pressure to build;
                # max_queue is sized out of reach so every shed in this
                # drill is the LADDER's decision, not the static
                # backstop's
                "max_batch": 8,
                "batch_window_us": 8000,
                "max_queue": 100000,
                "cache_size": 0,  # cache hits would fake infinite capacity
                "encoded_cache_size": 0,
            },
            "overload": {
                "enabled": True,
                "target_delay_ms": 50.0,
                "interval_ms": 50.0,
                "hysteresis_ms": hysteresis_s * 1e3,
                "dwell_ms": 25.0,
                "throttle_window_s": 5.0,
            },
        }
    )
    reg = Registry(cfg)
    store = reg.store()
    objs = [f"o{i}" for i in range(64)]
    store.transact_relation_tuples([_tup(o) for o in objs], [])
    checker = reg.checker()
    controller = reg.overload()
    violations = _Violations()
    rng = random.Random(seed)

    def crit_for(i: int) -> str:
        # 8/62/30 critical/default/sheddable: the critical slice of a
        # 10x burst (0.8x capacity) must fit under capacity alone, or no
        # admission policy could serve it all without shedding critical
        r = i % 50
        return CRITICAL if r < 4 else (DEFAULT if r < 35 else SHEDDABLE)

    budget = RetryBudget(ratio=0.1)
    attempts = [0]
    stats_lock = threading.Lock()
    # per-class: [accepted, shed, culled]; plus first-shed timestamps
    # (admission sheds only) for the ordering invariant
    by_class = {c: [0, 0, 0] for c in (CRITICAL, DEFAULT, SHEDDABLE)}
    first_shed: dict = {}
    accepted_lat: list[float] = []
    last_accept = [0.0]

    def one_check(i: int, crit: str, retry: bool) -> None:
        """One client request: check, and on a shed spend the shared
        retry budget for exactly one immediate retry."""
        budget.on_request()
        for attempt in (0, 1):
            t0 = time.perf_counter()
            with stats_lock:
                attempts[0] += 1
            try:
                checker.check(
                    _tup(objs[i % len(objs)]),
                    timeout=PER_OP_TIMEOUT_S,
                    criticality=crit,
                )
            except ErrResourceExhausted as e:
                # the CoDel cull (queued work dropped for aging past the
                # delay target) is latency protection, not an admission
                # decision — keep it out of the shed-ordering accounting
                is_cull = "culled" in str(e)
                with stats_lock:
                    if is_cull:
                        by_class[crit][2] += 1
                    else:
                        by_class[crit][1] += 1
                        first_shed.setdefault(crit, time.perf_counter())
                if retry and attempt == 0 and budget.spend():
                    continue
                return
            except KetoError:
                return  # typed transient: not this drill's concern
            except Exception as e:  # noqa: BLE001
                violations.add(f"untyped error from check: {e!r}")
                return
            with stats_lock:
                by_class[crit][0] += 1
                accepted_lat.append(time.perf_counter() - t0)
                last_accept[0] = time.perf_counter()
            return

    # -- phase 1: closed-loop capacity measurement ---------------------------
    # capacity is a supremum: a scheduler stall can only DEPRESS a
    # closed-loop window, never inflate it, so the max over two windows
    # is the robust estimate — an under-measured capacity would make
    # the "10x" burst not actually exceed the engine and the ladder
    # (correctly) never engage
    warm_s = 1.0 if smoke else 2.0
    n_workers = 16
    counted = [0]
    t_end = [0.0]

    def closed_loop(idx: int) -> None:
        i = idx
        while time.perf_counter() < t_end[0]:
            one_check(i, DEFAULT, retry=False)
            i += n_workers
            with stats_lock:
                counted[0] += 1

    capacity = 0.0
    for _ in range(2):
        with stats_lock:
            counted[0] = 0
        t_end[0] = time.perf_counter() + warm_s
        with ThreadPoolExecutor(max_workers=n_workers) as pool:
            list(pool.map(closed_loop, range(n_workers)))
        capacity = max(capacity, counted[0] / warm_s)
    if capacity <= 0:
        violations.add("capacity measurement served nothing")
        capacity = 1.0
    # reset the per-class accounting: only the burst is asserted on
    with stats_lock:
        for c in by_class:
            by_class[c] = [0, 0, 0]
        first_shed.clear()
        accepted_lat.clear()
        attempts[0] = 0

    # -- phase 2: 10x open-loop burst ----------------------------------------
    # offered rate is fixed at 10x capacity regardless of completions
    # (open loop); total submissions bounded so CI wall time stays sane
    burst_s = min(3.0 if smoke else 6.0, 30000.0 / (10.0 * capacity))
    offered_rate = 10.0 * capacity
    n_offered = int(offered_rate * burst_s)
    requests_made = [0]
    t_burst0 = time.perf_counter()
    pool = ThreadPoolExecutor(max_workers=128)
    try:
        tick_s = 0.005
        i = 0
        # the ladder can step back down before the last submission
        # lands (hysteresis is short by design here), so the "did the
        # shed rungs engage" check needs the max state seen across the
        # burst, not a point sample at the end
        burst_state = 0
        while i < n_offered:
            tick_deadline = time.perf_counter() + tick_s
            target = min(
                n_offered,
                int((time.perf_counter() - t_burst0) * offered_rate)
                + int(offered_rate * tick_s),
            )
            while i < target:
                pool.submit(one_check, rng.randrange(1 << 20),
                            crit_for(i), True)
                requests_made[0] += 1
                i += 1
            burst_state = max(burst_state, controller.state())
            now = time.perf_counter()
            if now < tick_deadline:
                time.sleep(tick_deadline - now)
        burst_state = max(burst_state, controller.state())
        pool.shutdown(wait=True)
    finally:
        pool.shutdown(wait=True)
    burst_wall = time.perf_counter() - t_burst0
    # goodput is measured to the LAST acceptance, not the full drain:
    # once the submitter stops, what remains in the pool is mostly the
    # shed/retry path (fast rejections against a dry budget) — wall
    # time spent draining it says nothing about how fast admitted work
    # was served
    served_wall = (
        last_accept[0] - t_burst0
        if last_accept[0] > t_burst0
        else burst_wall
    )
    goodput = sum(v[0] for v in by_class.values()) / max(served_wall, 1e-9)
    goodput_frac = goodput / capacity
    amplification = attempts[0] / max(1, requests_made[0])

    # -- phase 3: load drops to ~0 — ladder must step back down --------------
    recover_deadline = time.perf_counter() + (4 * hysteresis_s + 2.0)
    recovered_in_s = None
    t_rec0 = time.perf_counter()
    while time.perf_counter() < recover_deadline:
        one_check(rng.randrange(1 << 20), DEFAULT, retry=False)
        if controller.state() == 0:
            recovered_in_s = time.perf_counter() - t_rec0
            break
        time.sleep(0.02)

    # -- invariants ----------------------------------------------------------
    if goodput_frac < 0.8:
        violations.add(
            f"goodput at 10x was {goodput_frac:.2f}x of capacity "
            f"({goodput:.0f}/s vs {capacity:.0f}/s), below the 0.8x floor"
        )
    if by_class[CRITICAL][1]:
        violations.add(
            f"{by_class[CRITICAL][1]} critical-class sheds — the ladder "
            "must never shed critical"
        )
    if by_class[CRITICAL][2]:
        violations.add(
            f"{by_class[CRITICAL][2]} critical-class culls — the CoDel "
            "cull must exempt critical entries"
        )
    if not by_class[SHEDDABLE][1]:
        violations.add("10x burst shed nothing sheddable — admission dead")
    if DEFAULT in first_shed and SHEDDABLE in first_shed:
        if first_shed[DEFAULT] < first_shed[SHEDDABLE]:
            violations.add(
                "a default-class request was shed before any "
                "sheddable-class request — brownout ordering violated"
            )
    if burst_state < 3:
        violations.add(
            f"the burst never engaged the shed rungs (state={burst_state})"
        )
    if amplification > 1.1:
        violations.add(
            f"retry amplification {amplification:.3f}x over the 1.1x "
            "budget ceiling"
        )
    lat = sorted(accepted_lat)
    accepted_p99 = _percentile(lat, 0.99)
    if accepted_p99 > P99_BUDGET_S:
        violations.add(
            f"accepted p99 {accepted_p99 * 1e3:.0f}ms over the "
            f"{P99_BUDGET_S}s budget — admitted work is not being "
            "served fresh"
        )
    if recovered_in_s is None:
        violations.add(
            f"ladder did not return to normal within "
            f"{4 * hysteresis_s + 2.0:.1f}s of the burst ending "
            f"(state={controller.state()})"
        )
    flight_overload = [
        r for r in reg.flight().records()
        if r.get("kind") == "overload"
    ]
    if not flight_overload:
        violations.add("no kind=overload flight records from the burst")

    checker.close()
    snap = controller.snapshot()
    return {
        "phase": "overload",
        "seed": seed,
        "capacity_per_s": round(capacity, 1),
        "offered_rate_per_s": round(offered_rate, 1),
        # the realized rate can trail the 10x attempt when the client
        # pool itself saturates; still well past capacity, which is what
        # the invariants need
        "offered_realized_per_s": round(
            requests_made[0] / max(burst_wall, 1e-9), 1
        ),
        "burst_s": round(burst_wall, 2),
        "served_wall_s": round(served_wall, 2),
        "goodput_per_s": round(goodput, 1),
        "goodput_frac": round(goodput_frac, 3),
        "burst_state": burst_state,
        "accepted_by_class": {c: v[0] for c, v in by_class.items()},
        "shed_by_class": {c: v[1] for c, v in by_class.items()},
        "culled_by_class": {c: v[2] for c, v in by_class.items()},
        "retry_amplification": round(amplification, 3),
        "accepted_p99_ms": round(accepted_p99 * 1e3, 2),
        "recovered_in_s": (
            round(recovered_in_s, 2) if recovered_in_s is not None else None
        ),
        "flight_transitions": len(flight_overload),
        "controller": snap,
        "violations": violations.items,
    }


def run_device_chaos(seed: int) -> dict:
    """--device-chaos: the device-fault & memory-pressure drills.

    Three seeded scenarios against one registry, asserting ZERO wrong
    answers and bounded recovery throughout:

    1. OOM bisection — ``device.oom`` armed 3x against one 120-row
       columnar batch: every answer must match the host oracle, the
       breaker must stay closed (no host-fallback escalation), and
       ``keto_device_oom_bisections_total`` must reach >= 3.
    2. Compile-failure quarantine — ``device.compile_fail`` armed: the
       failing shape is absorbed into the quarantine (host oracle answers
       it) WITHOUT opening the circuit for every other shape.
    3. Device loss — ``device.lost`` armed: the lost batch is answered by
       the host oracle, the supervisor runs its failover/re-probe loop,
       and serving must return to device mode inside a bounded window,
       visible in the supervisor timeline and the flight recorder.
    """
    from keto_tpu.relationtuple.columns import CheckColumns

    recovery_bound_s = 15.0
    FAULTS.reset()
    rng = random.Random(seed)
    violations = _Violations()
    cfg = Config(
        values={
            "namespaces": [{"id": 1, "name": "n"}],
            "log": {"level": "error"},
            "engine": {
                "mode": "device",
                "max_batch": 256,
                "cache_size": 0,  # a cache hit would mask device faults
                "encoded_cache_size": 0,
                "fallback_threshold": 3,
                "fallback_cooldown_ms": 100,
                # inproc probe: the drill runs on the CPU test mesh where
                # a child re-probe proves nothing and costs a process spawn
                "failover": {
                    "probe_mode": "inproc",
                    "probe_interval_s": 0.05,
                },
            },
        }
    )
    reg = Registry(cfg)
    store = reg.store()
    true_objs = [f"ok{i}" for i in range(48)]
    store.transact_relation_tuples([_tup(o) for o in true_objs], [])
    checker = reg.checker()
    breaker = reg._engine_breaker
    supervisor = reg.device_supervisor()

    def counter(name: str) -> float:
        m = reg.metrics()._metrics.get(name)
        return float(m.value) if m is not None else 0.0

    def batch(n_rows: int):
        """(validated CheckColumns, expected answers): half present
        objects, half ghosts — wrong answers are detectable both ways."""
        objs, want = [], []
        for _ in range(n_rows):
            if rng.random() < 0.5:
                objs.append(true_objs[rng.randrange(len(true_objs))])
                want.append(True)
            else:
                objs.append(f"ghost{rng.randrange(64)}")
                want.append(False)
        cols = CheckColumns(
            ["n"] * n_rows, objs, ["view"] * n_rows,
            subject_ids=["alice"] * n_rows,
        )
        return cols.validate(), want

    def wrong_count(cols_want, label: str) -> int:
        cols, want = cols_want
        got = checker.check_batch_columnar(cols, 5)
        wrong = sum(1 for g, w in zip(got, want) if bool(g) is not w)
        if wrong:
            violations.add(f"{label}: {wrong}/{len(want)} wrong answers")
        return wrong

    # -- drill 1: OOM bisection ---------------------------------------------
    fb_before = counter("keto_device_fallback_batches_total")
    FAULTS.arm("device.oom", times=3)
    wrong_count(batch(120), "oom drill")
    bisections = counter("keto_device_oom_bisections_total")
    if bisections < 3:
        violations.add(
            f"oom drill: expected >= 3 bisections, saw {bisections}"
        )
    if counter("keto_device_fallback_batches_total") > fb_before:
        violations.add("oom drill: escalated to host fallback")
    if breaker.circuit_open():
        violations.add("oom drill: tripped the breaker")

    # -- drill 2: compile-failure quarantine --------------------------------
    FAULTS.arm("device.compile_fail")
    wrong_count(batch(96), "compile-fail drill")  # oracle absorbs the shape
    if not breaker.quarantine_snapshot():
        violations.add("compile-fail drill: shape was not quarantined")
    if breaker.circuit_open():
        violations.add("compile-fail drill: quarantine opened the circuit")
    quarantine_size = counter("keto_compile_quarantine_size")

    # -- drill 3: device loss -> failover -> bounded recovery ---------------
    failovers_before = counter("keto_backend_failovers_total")
    FAULTS.arm("device.lost")
    t_lost = time.monotonic()
    wrong_count(batch(64), "device-lost drill (during loss)")
    status = None
    deadline = t_lost + recovery_bound_s
    while time.monotonic() < deadline:
        status = supervisor.status() if supervisor is not None else None
        if (
            status is not None
            and status["failovers"] >= 1
            and not status["recovering"]
        ):
            break
        time.sleep(0.05)
    else:
        violations.add(
            f"device-lost drill: no recovery inside {recovery_bound_s}s "
            f"(status={status})"
        )
    if counter("keto_backend_failovers_total") <= failovers_before:
        violations.add("device-lost drill: failover counter did not move")
    # recovery ends with a forced half-open probe: the next batch must be
    # served by the device again with the circuit closing behind it
    wrong_count(batch(64), "device-lost drill (after recovery)")
    if breaker.circuit_open():
        violations.add("device-lost drill: circuit still open post-recovery")
    flight = reg.flight()
    failover_records = [
        r
        for r in (flight.records(200) if flight is not None else [])
        if r.get("kind") == "device_failover"
    ]
    if not failover_records:
        violations.add(
            "device-lost drill: no device_failover flight records"
        )

    FAULTS.reset()
    checker.close()
    if supervisor is not None:
        supervisor.stop()
    return {
        "phase": "device_chaos",
        "seed": seed,
        "oom_bisections": bisections,
        "compile_quarantine_size": quarantine_size,
        "failovers": counter("keto_backend_failovers_total"),
        "last_recovery_s": (
            status.get("last_recovery_s") if status is not None else None
        ),
        "failover_flight_records": len(failover_records),
        "violations": violations.items,
    }


def run_pool_soak(seed: int, n_rounds: int = 3, per_round: int = 4) -> dict:
    """The fork phase: 3-worker replica pool under delta.drop/delta.slow/
    replica.crash; every committed write must converge to 200 on fresh
    connections (the resync/respawn machinery is what's under test)."""
    import asyncio

    import httpx

    rng = random.Random(seed + 1)
    FAULTS.reset()
    # armed BEFORE the fork so every replica inherits it: each child
    # crashes applying its first delta, and the supervisor must respawn
    # the whole pool from the zygote (the existing drill in
    # tests/test_faults.py::test_inherited_replica_crash_fault_heals)
    FAULTS.arm("replica.crash")
    cfg = Config(
        values={
            "namespaces": [{"id": 1, "name": "n"}],
            "log": {"level": "error"},
            "serve": {
                "read": {"port": 0, "host": "127.0.0.1", "workers": 3},
                "write": {"port": 0, "host": "127.0.0.1"},
            },
        }
    )
    reg = Registry(cfg)
    loop = asyncio.new_event_loop()
    threading.Thread(target=loop.run_forever, daemon=True).start()
    violations = _Violations()
    injected: list[dict] = []
    t_start = time.perf_counter()
    try:
        rp, wp = asyncio.run_coroutine_threadsafe(
            reg.start_all(), loop
        ).result(timeout=120)
        # parent disarms NOW: respawn commands carry the parent's current
        # snapshot, so replacements come back clean and the pool heals
        FAULTS.disarm("replica.crash")
        injected.append({"round": -1, "site": "replica.crash"})

        def put(obj: str) -> None:
            body = {
                "namespace": "n", "object": obj, "relation": "view",
                "subject_id": "alice",
            }
            r = httpx.put(
                f"http://127.0.0.1:{wp}/relation-tuples",
                json=body, timeout=30,
            )
            if r.status_code != 201:
                violations.add(f"write {obj} failed: {r.status_code}")

        def converges(obj: str, timeout: float = 60.0) -> bool:
            params = {
                "namespace": "n", "object": obj, "relation": "view",
                "subject_id": "alice",
            }
            deadline = time.time() + timeout
            streak = 0
            while streak < 12 and time.time() < deadline:
                try:  # fresh connection per probe: covers every replica
                    r = httpx.get(
                        f"http://127.0.0.1:{rp}/check",
                        params=params, timeout=10,
                    )
                    streak = streak + 1 if r.status_code == 200 else 0
                except httpx.HTTPError:
                    streak = 0
                time.sleep(0.01)
            return streak >= 12

        wrote: list[str] = []
        for rnd in range(n_rounds):
            site = ("delta.drop", "delta.slow")[rng.randrange(2)]
            if site == "delta.slow":
                FAULTS.arm_slow(site, sleep_ms=200)
            else:
                FAULTS.arm(site)
            injected.append({"round": rnd, "site": site})
            for i in range(per_round):
                obj = f"pool{rnd}_{i}"
                put(obj)
                wrote.append(obj)
            FAULTS.reset()  # respawn snapshots must come back clean
            for obj in wrote[-per_round:]:
                if not converges(obj):
                    violations.add(
                        f"{obj} never converged after {site} round"
                    )
        # everything ever written still answers everywhere
        for obj in (wrote[0], wrote[-1]):
            if not converges(obj):
                violations.add(f"{obj} lost after the full soak")
        m = reg.metrics()._metrics
        respawn_count = (
            m["keto_replica_respawns_total"].value
            if "keto_replica_respawns_total" in m
            else 0
        )
        if respawn_count < 1:
            violations.add(
                "inherited replica.crash produced no respawns — the "
                "supervisor/zygote heal path never ran"
            )
        summary = {
            "phase": "pool",
            "seed": seed,
            "writes": len(wrote),
            "wall_s": round(time.perf_counter() - t_start, 2),
            "faults_injected": injected,
            "respawns": respawn_count,
            "resyncs": m["keto_replica_resyncs_total"].value
            if "keto_replica_resyncs_total" in m
            else 0,
            "violations": violations.items,
        }
        return summary
    finally:
        FAULTS.reset()
        try:
            asyncio.run_coroutine_threadsafe(reg.stop_all(), loop).result(
                timeout=30
            )
        except Exception:
            pass
        loop.call_soon_threadsafe(loop.stop)


def _tree_sig(tree):
    """Order-independent canonical form of an expand tree for parity
    comparison (children arrive in store insertion order, which differs
    between a recovered store and a freshly rebuilt oracle)."""
    if tree is None:
        return None
    d = tree.to_dict()

    def canon(node):
        kids = node.get("children")
        if kids:
            node["children"] = sorted(
                (canon(k) for k in kids), key=lambda n: json.dumps(n, sort_keys=True)
            )
        return node

    return json.dumps(canon(d), sort_keys=True)


def run_restart_drill(seed: int, ops_per_cycle: int = 40) -> dict:
    """Kill-and-restart drill: SIGKILL the writer at every seeded crash
    fault under ``wal.sync=always`` and assert zero acked-write loss.

    For each store kind (memory, columnar) the drill runs a child process
    per cycle (clean warm-up with mid-run + shutdown checkpoints, then one
    cycle per crash site, then a clean verify). The child prints an INTENT
    line before each mutator and an ACK line after it returns; a fired
    fault SIGKILLs the child mid-protocol. After every child exits, the
    parent recovers the directory READ-ONLY and asserts:

    - no WAL gap, and a checkpoint is in play after the first cycle
      (replay alone must not carry the whole history);
    - the recovered tuple set is exactly the acked oracle — plus, only
      for ``wal.crash_after_append``, the one durable-but-unacked op
      (written + fsynced before the kill: recovering it is correct);
    - the recovered snaptoken matches the same rule and never regresses;
    - Check AND Expand parity between the recovered store and a fresh
      in-memory shadow oracle holding the same tuples.
    """
    import shutil
    import subprocess
    import tempfile

    from keto_tpu.engine.check import CheckEngine
    from keto_tpu.engine.expand import ExpandEngine
    from keto_tpu.relationtuple.definitions import SubjectSet
    from keto_tpu.store import (
        ColumnarTupleStore,
        InMemoryTupleStore,
        recover_store,
    )
    from keto_tpu.store.wal import decode_tuple, encode_tuple

    t0 = time.monotonic()
    viol = _Violations()
    cycles_run = 0
    crash_sites = (
        "wal.crash_after_append",
        "wal.torn_write",
        "wal.corrupt_crc",
        "checkpoint.crash_mid_write",
    )
    for kind, store_cls in (
        ("memory", InMemoryTupleStore),
        ("columnar", ColumnarTupleStore),
    ):
        root = tempfile.mkdtemp(prefix=f"keto-restart-{kind}-")
        wal_dir = os.path.join(root, "wal")
        ckpt_dir = os.path.join(wal_dir, "checkpoints")
        oracle: set = set()  # acked tuple state (encoded, hashable)
        last_ack_version = 0
        prev_recovered_version = 0
        try:
            schedule = [None] + list(crash_sites) + [None]
            for cycle, site in enumerate(schedule):
                tag = f"{kind}/cycle{cycle}/{site or 'clean'}"
                spec = {
                    "dir": wal_dir,
                    "kind": kind,
                    "site": site,
                    # past the mid-cycle point so crashes land on a
                    # non-empty uncheckpointed suffix
                    "fault_at": (ops_per_cycle * 2) // 3 if site else None,
                    "ops": ops_per_cycle,
                    "seed": seed,
                    "cycle": cycle,
                }
                proc = subprocess.run(
                    [sys.executable, os.path.abspath(__file__),
                     "--restart-child", json.dumps(spec)],
                    capture_output=True, text=True, timeout=180,
                )
                lines = []
                for raw in proc.stdout.splitlines():
                    try:
                        lines.append(json.loads(raw))
                    except json.JSONDecodeError:
                        viol.add(f"{tag}: undecodable child line {raw!r}")
                crashed = any("crashed" in l for l in lines)
                done = any("done" in l for l in lines)
                if site and not crashed:
                    viol.add(f"{tag}: armed fault never fired "
                             f"(rc={proc.returncode})")
                    continue
                if not site and not done:
                    viol.add(
                        f"{tag}: clean cycle did not complete "
                        f"(rc={proc.returncode}, stderr tail: "
                        f"{proc.stderr[-400:]!r})"
                    )
                    continue
                cycles_run += 1

                # -- child's own recovery report for this cycle ---------------
                rec = next((l for l in lines if l.get("recovered")), None)
                if rec is None:
                    viol.add(f"{tag}: child printed no recovery line")
                    continue
                if rec["gap"]:
                    viol.add(f"{tag}: child recovery reported a WAL gap")
                if cycle >= 1 and rec["checkpoint_version"] == 0:
                    viol.add(f"{tag}: recovery ran without a checkpoint "
                             "(full-history replay)")
                if rec["version"] < prev_recovered_version:
                    viol.add(
                        f"{tag}: snaptoken regressed across restart: "
                        f"{prev_recovered_version} -> {rec['version']}"
                    )

                # -- fold this cycle's acked ops into the oracle --------------
                acked = {l["ack"] for l in lines if "ack" in l}
                intents = [l for l in lines if "op" in l]
                uncertain = None
                for intent in intents:
                    key = tuple(intent["t"])
                    if intent["op"] in acked:
                        if intent["k"] == "w":
                            oracle.add(key)
                        else:
                            oracle.discard(key)
                    elif uncertain is None:
                        uncertain = intent
                    else:
                        viol.add(f"{tag}: more than one unacked intent")
                versions = [l["version"] for l in lines if "ack" in l]
                if versions and versions != sorted(versions):
                    viol.add(f"{tag}: ack versions not monotonic")
                if versions:
                    last_ack_version = versions[-1]

                # -- parent-side read-only recovery + invariants --------------
                recovered = store_cls()
                rep = recover_store(recovered, wal_dir, ckpt_dir)
                if rep.gap:
                    viol.add(f"{tag}: parent recovery reported a WAL gap: "
                             f"{rep.notes}")
                got = {tuple(encode_tuple(t)) for t in recovered.all_tuples()}
                expect = set(oracle)
                expect_version = last_ack_version
                if site == "wal.crash_after_append" and uncertain is not None:
                    # the killed op's record was durable (written + fsynced)
                    # before the kill: recovery MUST surface it
                    key = tuple(uncertain["t"])
                    if uncertain["k"] == "w":
                        expect.add(key)
                    else:
                        expect.discard(key)
                    expect_version = last_ack_version + 1
                if got != expect:
                    viol.add(
                        f"{tag}: acked-write divergence: "
                        f"{len(expect - got)} lost, "
                        f"{len(got - expect)} phantom"
                    )
                else:
                    # adopt: the durable-but-unacked op (if any) is now
                    # part of the baseline the next cycle builds on
                    oracle = expect
                if rep.final_version != expect_version:
                    viol.add(
                        f"{tag}: recovered snaptoken {rep.final_version} "
                        f"!= expected {expect_version}"
                    )
                last_ack_version = max(last_ack_version, rep.final_version)
                prev_recovered_version = rep.final_version

                # -- Check/Expand parity vs a fresh shadow oracle -------------
                tuples = recovered.all_tuples()
                shadow = InMemoryTupleStore()
                if tuples:
                    shadow.write_relation_tuples(*tuples)
                ce_r = CheckEngine(recovered)
                ce_s = CheckEngine(shadow)
                for t in tuples[:25]:
                    if not ce_r.subject_is_allowed(t):
                        viol.add(f"{tag}: recovered store denies {t}")
                    if not ce_s.subject_is_allowed(t):
                        viol.add(f"{tag}: shadow oracle denies {t}")
                for j in range(8):
                    probe = decode_tuple(
                        ["n", f"absent{j}", "view", 0, "nobody"]
                    )
                    if ce_r.subject_is_allowed(probe) or ce_s.subject_is_allowed(
                        probe
                    ):
                        viol.add(f"{tag}: phantom allow for {probe}")
                ee_r = ExpandEngine(recovered)
                ee_s = ExpandEngine(shadow)
                for obj in sorted({t.object for t in tuples})[:5]:
                    ss = SubjectSet(namespace="n", object=obj, relation="view")
                    if _tree_sig(ee_r.build_tree(ss)) != _tree_sig(
                        ee_s.build_tree(ss)
                    ):
                        viol.add(f"{tag}: expand divergence on {obj}")
        finally:
            shutil.rmtree(root, ignore_errors=True)
    return {
        "phase": "restart",
        "cycles": cycles_run,
        "elapsed_s": round(time.monotonic() - t0, 2),
        "violations": viol.items,
    }


def run_promotion_drill(seed: int, ops: int = 60) -> dict:
    """SIGKILL-the-leader drill: a follower seeded from the leader's
    checkpoint and tailing its WAL gets promoted off the dead leader's
    log (shared-disk failover) and must hold EVERY acked write.

    The leader child acks each write only after its WAL frame is durable
    (sync=always) and kills itself mid-stream right after one durable-
    but-unacked write — so the drill asserts the full WAL-before-ack
    contract: zero acked writes lost, ack tokens monotonic, at most the
    one unacked op surfacing as a recovered extra, and the promoted node
    serving at-least-latest reads with no residual lag."""
    import shutil
    import signal
    import subprocess
    import tempfile

    from keto_tpu.replication.follower import FollowerReplicator
    from keto_tpu.replication.token import parse_snaptoken
    from keto_tpu.store import InMemoryTupleStore
    from keto_tpu.store.wal import encode_tuple

    t0 = time.monotonic()
    viol = _Violations()
    root = tempfile.mkdtemp(prefix="keto-promotion-")
    wal_dir = os.path.join(root, "wal")
    scratch = os.path.join(root, "follower")
    rng = random.Random(seed + 31)
    kill_at = rng.randrange((ops * 2) // 3, ops - 2)
    spec = {"dir": wal_dir, "ops": ops, "seed": seed, "kill_at": kill_at}
    follower = None
    proc = None
    summary = {"phase": "promotion", "seed": seed, "kill_at": kill_at}
    try:
        proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__),
             "--promotion-child", json.dumps(spec)],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True,
        )
        lines: list[dict] = []

        def _take(raw: str):
            try:
                doc = json.loads(raw)
            except json.JSONDecodeError:
                viol.add(f"promotion: undecodable child line {raw!r}")
                return None
            lines.append(doc)
            return doc

        port = None
        for raw in proc.stdout:
            doc = _take(raw)
            if doc and doc.get("ready"):
                port = doc["port"]
                break
        if port is None:
            err = proc.stderr.read()[-400:] if proc.stderr else ""
            viol.add(f"promotion: leader child never became ready ({err!r})")
            return {**summary, "violations": viol.items}

        follower = FollowerReplicator(
            InMemoryTupleStore(),
            f"http://127.0.0.1:{port}",
            scratch_dir=scratch,
            poll_interval_s=0.01,
            wait_ms=200.0,
        )
        follower.start()
        if follower.store.version <= 0:
            viol.add(
                "promotion: follower did not seed from the leader's "
                "checkpoint"
            )
        proc.stdin.write("go\n")
        proc.stdin.flush()
        for raw in proc.stdout:  # drains until SIGKILL closes the pipe
            _take(raw)
        rc = proc.wait(timeout=60)
        if any("done" in l for l in lines):
            viol.add(
                f"promotion: leader was never killed (kill_at={kill_at}, "
                f"rc={rc})"
            )

        acked = {l["ack"] for l in lines if "ack" in l}
        intents = {l["op"]: tuple(l["t"]) for l in lines if "op" in l}
        oracle = {intents[i] for i in acked}  # insert-only stream
        unacked = {intents[i] for i in intents if i not in acked}
        versions = [l["version"] for l in lines if "ack" in l]
        token_versions = [
            parse_snaptoken(l["token"]).version
            for l in lines
            if "token" in l
        ]
        if token_versions != sorted(token_versions):
            viol.add("promotion: write-ack snaptokens not monotonic")
        if versions and token_versions and versions != token_versions:
            viol.add("promotion: ack token version != store version")
        tailed_live = follower.applied_total

        rep = follower.promote(wal_dir)
        if rep["gap"]:
            viol.add("promotion: replayed leader log had gaps")
        last_ack = versions[-1] if versions else 0
        if rep["final_version"] < last_ack:
            viol.add(
                f"promotion: final version {rep['final_version']} < last "
                f"acked {last_ack} — acked writes lost"
            )
        got = {
            tuple(encode_tuple(t)) for t in follower.store.all_tuples()
        }
        lost = oracle - got
        if lost:
            viol.add(
                f"promotion: {len(lost)} acked writes missing after "
                "promotion"
            )
        phantom = got - oracle - unacked
        if phantom:
            viol.add(
                f"promotion: {len(phantom)} phantom tuples after promotion"
            )
        try:
            # a promoted node is the authority: zero-window at-least-
            # latest reads must pass with no residual lag
            follower.wait_for_version(rep["final_version"], timeout_s=0.0)
        except KetoError as e:
            viol.add(f"promotion: promoted node still lagging: {e!r}")
        if follower.role != "leader":
            viol.add(f"promotion: role is {follower.role!r} after promote")
        summary.update(
            {
                "acked_ops": len(acked),
                "tailed_live": tailed_live,
                "promote_applied": rep["applied"],
                "final_version": rep["final_version"],
                "elapsed_s": round(time.monotonic() - t0, 2),
                "violations": viol.items,
            }
        )
        return summary
    finally:
        if follower is not None:
            follower.stop()
        if proc is not None and proc.poll() is None:
            proc.send_signal(signal.SIGKILL)
            proc.wait(timeout=10)
        shutil.rmtree(root, ignore_errors=True)


class _GamedayFollower:
    """One follower of the game-day fleet: a real FollowerReplicator
    tailing the leader, a real ElectionManager over the shared lease,
    and an aiohttp surface whose ``/replication/*`` routes come alive
    the moment this node is promoted (the deferred-route pattern the
    write plane uses)."""

    def __init__(self, name: str, wal_dir: str, upstream: str,
                 scratch: str, loop, *, ttl: float, hb: float):
        from aiohttp import web

        from keto_tpu.cluster.election import (
            ElectionManager,
            LeaseStore,
            PromotedReplicationSource,
        )
        from keto_tpu.replication.follower import FollowerReplicator
        from keto_tpu.store import InMemoryTupleStore

        self.name = name
        self.wal_dir = wal_dir
        self.store = InMemoryTupleStore()
        self.rep = FollowerReplicator(
            self.store, upstream, scratch_dir=scratch,
            poll_interval_s=0.01,
        )
        self.promoted_src = None
        self._src_cls = PromotedReplicationSource

        async def h_status(request):
            src = self.promoted_src
            if src is not None:
                return await src.handle_status(request)
            return web.json_response(self.rep.lag())

        async def h_blocked(request):
            src = self.promoted_src
            if src is not None:
                if request.path.endswith("/checkpoint"):
                    return await src.handle_checkpoint(request)
                return await src.handle_wal(request)
            return web.json_response(
                {"error": "not the replication leader"}, status=503
            )

        app = web.Application()
        app.router.add_get("/replication/status", h_status)
        app.router.add_get("/replication/checkpoint", h_blocked)
        app.router.add_get("/replication/wal", h_blocked)

        async def _serve() -> int:
            runner = web.AppRunner(app)
            await runner.setup()
            site = web.TCPSite(runner, "127.0.0.1", 0)
            await site.start()
            return site._server.sockets[0].getsockname()[1]

        import asyncio

        self.port = asyncio.run_coroutine_threadsafe(
            _serve(), loop
        ).result(timeout=60)
        self.write_url = f"http://127.0.0.1:{self.port}"
        self.em = ElectionManager(
            LeaseStore(wal_dir),
            instance_id=name,
            lease_ttl_s=ttl,
            heartbeat_interval_s=hb,
            write_url=self.write_url,
            promote_fn=self._promote,
            retarget_fn=lambda lease: self.rep.retarget(
                str(lease.get("write_url") or "")
            ),
            position_fn=lambda: self.store.version,
        )

    def _promote(self) -> dict:
        report = self.rep.promote(self.wal_dir)
        src = self._src_cls(self.store, self.wal_dir)
        src.open()
        self.promoted_src = src
        return report

    def start(self) -> None:
        self.rep.start()
        self.em.start()

    def stop(self) -> None:
        self.em.stop()
        if self.promoted_src is not None:
            self.promoted_src.close()
        self.rep.stop()


def run_election_drill(seed: int, ops: int = 60) -> dict:
    """Game day: SIGKILL the elected leader mid-traffic and watch the
    fleet drive itself. One leader child (durable store + replication
    routes + the lease), two in-process followers tailing it, each
    running a real ElectionManager over the shared WAL directory.

    Asserted, in the order the ISSUE states them:

    - a new leader holds the lease within the failover budget (the dead
      leader's lease had at most one TTL to run, plus campaign time);
    - ZERO acked writes are lost — the shadow oracle built from the
      child's INTENT/ACK stream is a subset of the promoted store; at
      most the one durable-but-unacked op surfaces as an extra;
    - reads never stop: a reader hammers both followers' stores through
      the whole window (kill included) with bounded p99 and no errors;
    - the fencing-token lineage on disk is exactly one strictly
      increasing chain, ending at the new leader's term;
    - the loser retargets its tail at the winner and converges on
      post-failover writes without a re-bootstrap.
    """
    import asyncio
    import shutil
    import signal
    import subprocess
    import tempfile

    from keto_tpu.cluster.election import LeaseStore
    from keto_tpu.relationtuple.definitions import RelationTuple, SubjectID
    from keto_tpu.store.wal import encode_tuple

    t0 = time.monotonic()
    viol = _Violations()
    root = tempfile.mkdtemp(prefix="keto-gameday-")
    wal_dir = os.path.join(root, "wal")
    ttl, hb = 2.0, 0.25
    rng = random.Random(seed + 47)
    kill_at = rng.randrange((ops * 2) // 3, ops - 2)
    spec = {
        "dir": wal_dir, "ops": ops, "seed": seed, "kill_at": kill_at,
        "ttl": ttl, "hb": hb,
    }
    summary = {"phase": "election", "seed": seed, "kill_at": kill_at,
               "lease_ttl_s": ttl}
    followers: list[_GamedayFollower] = []
    proc = None
    loop = asyncio.new_event_loop()
    threading.Thread(target=loop.run_forever, daemon=True).start()
    read_errors: list[str] = []
    read_lat: list[float] = []
    stop_reads = threading.Event()
    try:
        proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__),
             "--election-child", json.dumps(spec)],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True,
        )
        lines: list[dict] = []

        def _take(raw: str):
            try:
                doc = json.loads(raw)
            except json.JSONDecodeError:
                viol.add(f"election: undecodable child line {raw!r}")
                return None
            lines.append(doc)
            return doc

        port = None
        for raw in proc.stdout:
            doc = _take(raw)
            if doc and doc.get("ready"):
                port = doc["port"]
                if doc.get("term") != 1:
                    viol.add(
                        f"election: bootstrap term {doc.get('term')} != 1"
                    )
                break
        if port is None:
            err = proc.stderr.read()[-400:] if proc.stderr else ""
            viol.add(f"election: leader child never became ready ({err!r})")
            return {**summary, "violations": viol.items}

        upstream = f"http://127.0.0.1:{port}"
        for i in range(2):
            f = _GamedayFollower(
                f"gameday-f{i}", wal_dir, upstream,
                os.path.join(root, f"f{i}"), loop, ttl=ttl, hb=hb,
            )
            followers.append(f)
        # seed each follower's peer cache so candidacy ranks are a total
        # order (equal priority/position ties break on instance id)
        members = [
            {"instance_id": f.name, "alive": True,
             "version": f.store.version, "election": {"priority": 0}}
            for f in followers
        ]
        for f in followers:
            f.em.observe_peers({"members": members})
            f.start()
        deadline = time.monotonic() + 30.0
        while any(f.store.version <= 0 for f in followers):
            if time.monotonic() > deadline:
                viol.add("election: followers never seeded from leader")
                return {**summary, "violations": viol.items}
            time.sleep(0.05)

        def reader() -> None:
            i = 0
            while not stop_reads.is_set():
                f = followers[i % len(followers)]
                i += 1
                t_r = time.monotonic()
                try:
                    _ = f.store.version
                    f.store.all_tuples()
                except Exception as e:  # noqa: BLE001
                    read_errors.append(repr(e))
                read_lat.append(time.monotonic() - t_r)
                time.sleep(0.005)

        reads = threading.Thread(target=reader, daemon=True)
        reads.start()

        proc.stdin.write("go\n")
        proc.stdin.flush()
        for raw in proc.stdout:  # drains until SIGKILL closes the pipe
            _take(raw)
        proc.wait(timeout=60)
        t_kill = time.monotonic()
        if any("done" in l for l in lines):
            viol.add(f"election: leader was never killed (kill_at={kill_at})")
        if any("fenced" in l for l in lines):
            viol.add("election: live leader saw its own fence fail")

        # -- a new leader within the failover budget ------------------------
        # the lease had at most one TTL to run at the kill; allow one
        # more TTL for detection + stagger + promotion (CI-safe, still
        # an order of magnitude under "page an operator")
        budget = 2.0 * ttl
        winner = None
        while winner is None and time.monotonic() - t_kill < budget + 5.0:
            winner = next(
                (f for f in followers if f.em.role == "leader"), None
            )
            if winner is None:
                time.sleep(0.02)
        failover_s = time.monotonic() - t_kill
        if winner is None:
            viol.add(f"election: no new leader within {budget + 5.0:.0f}s")
            return {**summary, "violations": viol.items}
        if failover_s > budget:
            viol.add(
                f"election: failover took {failover_s:.2f}s "
                f"(budget {budget:.2f}s = 2x lease TTL)"
            )
        loser = next(f for f in followers if f is not winner)

        # -- zero acked-write loss (shadow-oracle parity) -------------------
        acked = {l["ack"] for l in lines if "ack" in l}
        intents = {l["op"]: tuple(l["t"]) for l in lines if "op" in l}
        oracle = {intents[i] for i in acked}
        unacked = {intents[i] for i in intents if i not in acked}
        got = {
            tuple(encode_tuple(t)) for t in winner.store.all_tuples()
        }
        lost = oracle - got
        if lost:
            viol.add(
                f"election: {len(lost)} acked writes missing on the "
                "promoted leader"
            )
        phantom = got - oracle - unacked
        if phantom:
            viol.add(
                f"election: {len(phantom)} phantom tuples on the "
                "promoted leader"
            )

        # -- exactly one fencing-token lineage ------------------------------
        lineage = LeaseStore(wal_dir).lineage()
        terms = [r["term"] for r in lineage]
        if terms != sorted(set(terms)) or any(
            b - a != 1 for a, b in zip(terms, terms[1:])
        ):
            viol.add(f"election: fencing lineage not one chain: {terms}")
        if not lineage or lineage[-1]["leader_id"] != winner.em.instance_id:
            viol.add(
                f"election: lineage tip {lineage[-1:]} is not the "
                f"winner {winner.em.instance_id}"
            )
        if sum(1 for f in followers if f.em.role == "leader") != 1:
            viol.add("election: more than one in-process leader")
        if not winner.em.is_writable():
            viol.add("election: winner fails its own fence check")
        if loser.em.is_writable():
            viol.add("election: LOSER passes the write fence")

        # -- the loser retargets and converges without re-bootstrap ---------
        post = []
        for i in range(5):
            t = RelationTuple(
                namespace="n", object=f"post{i}", relation="view",
                subject=SubjectID(id="u0"),
            )
            if not winner.em.is_writable():
                viol.add("election: winner lost writability mid-write")
                break
            winner.store.write_relation_tuples(t)
            post.append(tuple(encode_tuple(t)))
        deadline = time.monotonic() + 15.0
        while (
            loser.store.version < winner.store.version
            and time.monotonic() < deadline
        ):
            time.sleep(0.05)
        if loser.rep.upstream.rstrip("/") != winner.write_url:
            viol.add(
                f"election: loser still tails {loser.rep.upstream!r}, "
                f"not the winner {winner.write_url!r}"
            )
        loser_got = {
            tuple(encode_tuple(t)) for t in loser.store.all_tuples()
        }
        missing_post = [p for p in post if p not in loser_got]
        if missing_post:
            viol.add(
                f"election: {len(missing_post)} post-failover writes "
                "never reached the retargeted loser"
            )

        # -- reads never stopped --------------------------------------------
        stop_reads.set()
        reads.join(timeout=5)
        if read_errors:
            viol.add(
                f"election: {len(read_errors)} read errors during "
                f"failover (first: {read_errors[0]})"
            )
        lat_sorted = sorted(read_lat)
        p99 = _percentile(lat_sorted, 0.99) if lat_sorted else 0.0
        if p99 > 1.0:
            viol.add(f"election: read p99 {p99:.3f}s over the 1s budget")
        if len(read_lat) < 50:
            viol.add(
                f"election: only {len(read_lat)} reads served — reads "
                "effectively stopped"
            )

        summary.update(
            {
                "acked_ops": len(acked),
                "failover_s": round(failover_s, 3),
                "winner": winner.em.instance_id,
                "winner_term": winner.em.term,
                "lineage_terms": terms,
                "reads_served": len(read_lat),
                "read_p99_s": round(p99, 4),
                "elapsed_s": round(time.monotonic() - t0, 2),
                "violations": viol.items,
            }
        )
        return summary
    finally:
        stop_reads.set()
        for f in followers:
            try:
                f.stop()
            except Exception:  # noqa: BLE001
                pass
        if proc is not None and proc.poll() is None:
            proc.send_signal(signal.SIGKILL)
            proc.wait(timeout=10)
        loop.call_soon_threadsafe(loop.stop)
        shutil.rmtree(root, ignore_errors=True)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seed", type=int, default=4)
    ap.add_argument(
        "--smoke", action="store_true",
        help="small deterministic tier for tools/check.sh",
    )
    ap.add_argument("--ops", type=int, default=0, help="reader ops total")
    ap.add_argument("--writes", type=int, default=0)
    ap.add_argument("--faults", type=int, default=0)
    ap.add_argument(
        "--pool", action="store_true",
        help="also run the forked replica-pool phase",
    )
    ap.add_argument(
        "--restart", action="store_true",
        help="also run the durable-store kill-and-restart drill",
    )
    ap.add_argument(
        "--device-chaos", action="store_true",
        help="also run the device-fault drills (OOM bisection, compile "
        "quarantine, device-loss failover)",
    )
    ap.add_argument(
        "--overload", action="store_true",
        help="also run the 10x open-loop overload drill (goodput floor, "
        "strict criticality shed ordering, retry-budget amplification "
        "cap, brownout ladder recovery)",
    )
    ap.add_argument(
        "--election", action="store_true",
        help="also run the game-day failover drill (SIGKILL the elected "
        "leader mid-traffic; assert failover within the lease budget, "
        "zero acked-write loss, bounded reads, one fencing lineage)",
    )
    args = ap.parse_args(argv)

    if args.smoke:
        ops, writes, faults = 800, 80, 5
    else:
        ops, writes, faults = 8000, 600, 24
    if args.ops:
        ops = args.ops
    if args.writes:
        writes = args.writes
    if args.faults:
        faults = args.faults

    phases = [run_engine_soak(args.seed, n_ops=ops, n_writes=writes,
                              n_faults=faults)]
    if args.device_chaos:
        phases.append(run_device_chaos(args.seed))
    if args.pool:
        phases.append(run_pool_soak(args.seed))
    if args.restart:
        phases.append(
            run_restart_drill(
                args.seed, ops_per_cycle=40 if args.smoke else 120
            )
        )
        phases.append(
            run_promotion_drill(args.seed, ops=60 if args.smoke else 150)
        )
    if args.election:
        phases.append(
            run_election_drill(args.seed, ops=60 if args.smoke else 150)
        )
    if args.overload:
        phases.append(run_overload_drill(args.seed, smoke=args.smoke))
    bad = [v for p in phases for v in p["violations"]]
    print(json.dumps({"phases": phases, "ok": not bad}, indent=2))
    if bad:
        print(f"SOAK FAILED: {len(bad)} violation(s)", file=sys.stderr)
        for v in bad:
            print(f"  - {v}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
