#!/usr/bin/env python
"""Seeded chaos soak: deterministic fault schedule under concurrent load.

Drives the full serving stack (registry -> breaker-wrapped device engine ->
batcher) with a mixed read-write workload while a seeded schedule arms
crash / slowness / garbage-output faults at fixed OPERATION COUNTS (not
wall-clock), so the same seed always injects the same faults at the same
points in the workload. Thread interleavings still vary run to run; every
invariant below is interleaving-independent:

- **Answer parity.** Tuples inserted before the soak and never touched
  must always check True; tuples never inserted must always check False;
  a tuple the writer has durably committed (insert-only set) must never
  check False when read at-least-as-fresh (min_version pinned to its
  commit). Transient TYPED errors (shed, crashed dispatcher, deadline)
  are tolerated during fault windows — wrong ANSWERS never are.
- **Snaptoken monotonicity.** The read-plane snaptoken never regresses.
- **No lost or double-resolved futures.** Every check resolves (answer or
  typed error) inside its per-op timeout — a lost future would surface as
  a timeout, a double-resolution as a decode-stage crash. Both count
  against the run. The pipeline must also drain to zero at the end.
- **Bounded tail latency.** p99 across the run (fault windows included)
  stays under a generous budget — a wedged stage or an unculled stuck
  batch blows it immediately.

A final parity sweep (faults cleared) compares every asserted tuple
against a fresh host oracle over the final store.

The optional pool phase (``--pool``) forks a 3-worker SO_REUSEPORT
replica pool and mixes the distribution faults the single process cannot
express — ``delta.drop`` (silent version gap -> resync handshake),
``delta.slow`` (stalled propagation), ``replica.crash`` (supervisor
respawn) — asserting every committed write converges to 200 on fresh
connections afterward.

Usage:
    python tools/soak.py --smoke --seed 4        # the tools/check.sh tier
    python tools/soak.py --seed 7 --ops 20000    # longer soak
    python tools/soak.py --smoke --pool          # include the fork phase

Exit 0 and a one-line summary JSON on stdout when every invariant holds;
exit 1 with the violation list otherwise.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import sys
import threading
import time
from concurrent.futures import TimeoutError as _FutTimeout

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from keto_tpu.driver import Config, Registry  # noqa: E402
from keto_tpu.faults import FAULTS  # noqa: E402
from keto_tpu.relationtuple.definitions import (  # noqa: E402
    RelationTuple,
    SubjectID,
)
from keto_tpu.utils.errors import KetoError  # noqa: E402

PER_OP_TIMEOUT_S = 10.0  # lost-future detector: no answer in this long
P99_BUDGET_S = 3.0  # generous; catches wedged stages, not CI jitter

#: the schedule draws from these (kind, site, arm kwargs). Slow sleeps are
#: kept far below PER_OP_TIMEOUT_S so a slept batch still resolves.
FAULT_MENU = (
    ("crash", "batcher.dispatcher_die", {}),
    ("crash", "device.compile_error", {"times": 2}),
    ("nan", "device.batch_nan", {}),
    ("slow", "device.slow", {"sleep_ms": 40, "times": 3}),
    ("slow", "batcher.dispatch_slow", {"sleep_ms": 25, "times": 3}),
)


def _tup(obj: str) -> RelationTuple:
    return RelationTuple(
        namespace="n", object=obj, relation="view",
        subject=SubjectID(id="alice"),
    )


class _Violations:
    def __init__(self):
        self.items: list[str] = []
        self._lock = threading.Lock()

    def add(self, msg: str) -> None:
        with self._lock:
            if len(self.items) < 50:  # bounded: one bad invariant can spam
                self.items.append(msg)


def _percentile(sorted_vals: list[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    return sorted_vals[min(len(sorted_vals) - 1, int(q * len(sorted_vals)))]


def run_engine_soak(
    seed: int,
    n_ops: int = 1200,
    n_readers: int = 4,
    n_writes: int = 120,
    n_faults: int = 6,
) -> dict:
    """The in-process phase: registry + breaker + batcher under load.
    Returns the summary dict; violations are listed under 'violations'."""
    rng = random.Random(seed)
    FAULTS.reset()
    cfg = Config(
        values={
            "namespaces": [{"id": 1, "name": "n"}],
            "log": {"level": "error"},
            "engine": {
                "mode": "device",
                "max_batch": 256,
                "cache_size": 0,  # a cache hit would mask engine faults
                "encoded_cache_size": 0,
                "fallback_threshold": 3,
                "fallback_cooldown_ms": 100,
            },
        }
    )
    reg = Registry(cfg)
    store = reg.store()

    static_true = [f"static{i}" for i in range(32)]
    static_false = [f"ghost{i}" for i in range(32)]
    store.transact_relation_tuples([_tup(o) for o in static_true], [])
    checker = reg.checker()

    # committed insert-only tuples: (object, min_version at/after commit)
    committed: list[tuple[str, int]] = []
    committed_lock = threading.Lock()
    violations = _Violations()
    ops_done = [0] * n_readers
    latencies: list[list[tuple[float, bool]]] = [[] for _ in range(n_readers)]
    tolerated: dict[str, int] = {}
    tol_lock = threading.Lock()
    timeouts = [0]
    stop = threading.Event()
    fault_window = threading.Event()  # any injected fault still pending

    # -- deterministic schedule: (trigger at total-op count, menu entry) ----
    schedule = sorted(
        (rng.randrange(n_ops // 8, n_ops), rng.choice(FAULT_MENU))
        for _ in range(n_faults)
    )
    injected: list[dict] = []

    def injector():
        pending = list(schedule)
        armed_sites: list[str] = []
        while not stop.is_set():
            total = sum(ops_done)
            while pending and pending[0][0] <= total:
                trigger, (kind, site, kw) = pending.pop(0)
                if kind == "slow":
                    FAULTS.arm_slow(site, **kw)
                else:
                    FAULTS.arm(site, **kw)
                armed_sites.append(site)
                injected.append(
                    {"at_op": trigger, "kind": kind, "site": site}
                )
                fault_window.set()
            if fault_window.is_set() and not any(
                FAULTS.armed(s) or FAULTS.slow_armed(s)
                for s in armed_sites
            ):
                fault_window.clear()  # everything injected was consumed
            if not pending and not fault_window.is_set():
                return
            stop.wait(0.002)

    def writer():
        wrote = 0
        while wrote < n_writes and not stop.is_set():
            obj = f"dyn{wrote}"
            churn = f"churn{wrote % 8}"
            before = store.version
            # churn tuples cycle insert/delete for version traffic; their
            # answers are never asserted. dyn tuples are insert-only, so
            # "committed => never False" holds at any later version.
            if wrote % 3 == 2:
                store.transact_relation_tuples([], [_tup(churn)])
            else:
                store.transact_relation_tuples(
                    [_tup(obj), _tup(churn)], []
                )
                with committed_lock:
                    committed.append((obj, store.version))
            if store.version <= before:
                violations.add(
                    f"store version did not advance: {before} -> "
                    f"{store.version}"
                )
            wrote += 1
            time.sleep(0.001)

    def classify(e: BaseException) -> None:
        name = type(e).__name__
        with tol_lock:
            tolerated[name] = tolerated.get(name, 0) + 1

    def reader(idx: int):
        r = random.Random(seed * 1000 + idx)
        my_ops = n_ops // n_readers
        for _ in range(my_ops):
            if stop.is_set():
                return
            roll = r.random()
            min_version = 0
            if roll < 0.4:
                obj, want = r.choice(static_true), True
            elif roll < 0.7:
                obj, want = r.choice(static_false), False
            else:
                with committed_lock:
                    if committed:
                        obj, min_version = r.choice(committed)
                        want = True
                    else:
                        obj, want = r.choice(static_true), True
            in_window = fault_window.is_set()
            t0 = time.perf_counter()
            try:
                got = checker.check(
                    _tup(obj),
                    timeout=PER_OP_TIMEOUT_S,
                    min_version=min_version,
                )
            except _FutTimeout:
                timeouts[0] += 1  # a lost future surfaces exactly here
            except KetoError as e:
                classify(e)  # typed + transient: tolerated, not correct-
                # ness — wrong answers below are the real violations
            except Exception as e:  # noqa: BLE001
                violations.add(f"untyped error from check: {e!r}")
            else:
                if got is not want:
                    violations.add(
                        f"wrong answer for {obj}: got {got}, want {want}"
                        f" (min_version={min_version})"
                    )
            latencies[idx].append((time.perf_counter() - t0, in_window))
            ops_done[idx] += 1

    def snaptoken_monitor():
        last = -1
        while not stop.is_set():
            v = int(reg.read_snaptoken())
            if v < last:
                violations.add(f"snaptoken regressed: {last} -> {v}")
            last = v
            stop.wait(0.005)

    threads = [
        threading.Thread(target=reader, args=(i,), daemon=True)
        for i in range(n_readers)
    ]
    threads += [
        threading.Thread(target=writer, daemon=True),
        threading.Thread(target=snaptoken_monitor, daemon=True),
    ]
    inj = threading.Thread(target=injector, daemon=True)
    t_start = time.perf_counter()
    for t in threads:
        t.start()
    inj.start()
    for t in threads[:n_readers]:
        t.join(timeout=300)
        if t.is_alive():
            violations.add("reader wedged past the soak budget")
    stop.set()
    inj.join(timeout=10)
    for t in threads[n_readers:]:
        t.join(timeout=10)
    wall_s = time.perf_counter() - t_start

    # -- drain + final parity sweep against the host oracle -----------------
    FAULTS.reset()  # disarm leftovers (e.g. an unconsumed slow arming)
    deadline = time.time() + 30
    stats = checker.pipeline_stats()
    while stats["batches_in_pipeline"] and time.time() < deadline:
        time.sleep(0.05)
        stats = checker.pipeline_stats()
    if stats["batches_in_pipeline"]:
        violations.add(
            f"pipeline did not drain: {stats['batches_in_pipeline']} "
            "batches still registered"
        )
    from keto_tpu.engine.check import CheckEngine

    oracle = CheckEngine(store, max_depth=5)
    with committed_lock:
        sweep = (
            [(o, True) for o in static_true]
            + [(o, False) for o in static_false]
            + [(o, True) for o, _v in committed]
        )
    parity_mismatches = 0
    for obj, want in sweep:
        o = oracle.subject_is_allowed(_tup(obj))
        try:
            c = checker.check(_tup(obj), timeout=PER_OP_TIMEOUT_S)
        except KetoError:
            c = None  # breaker may still be cooling down; oracle is truth
        if o is not want or (c is not None and c is not o):
            parity_mismatches += 1
            violations.add(
                f"parity sweep: {obj} oracle={o} served={c} want={want}"
            )

    all_lat = sorted(l for per in latencies for (l, _w) in per)
    window_lat = sorted(l for per in latencies for (l, w) in per if w)
    p99 = _percentile(all_lat, 0.99)
    if p99 > P99_BUDGET_S:
        violations.add(f"p99 {p99 * 1e3:.0f}ms over {P99_BUDGET_S}s budget")
    if timeouts[0]:
        violations.add(f"{timeouts[0]} checks timed out (lost futures?)")

    checker.close()
    summary = {
        "phase": "engine",
        "seed": seed,
        "ops": sum(ops_done),
        "wall_s": round(wall_s, 2),
        "faults_injected": injected,
        "tolerated_errors": tolerated,
        "timeouts": timeouts[0],
        "p50_ms": round(_percentile(all_lat, 0.50) * 1e3, 2),
        "p99_ms": round(p99 * 1e3, 2),
        "p99_fault_window_ms": round(
            _percentile(window_lat, 0.99) * 1e3, 2
        ),
        "deadline_culls": stats.get("deadline_expired", {}),
        "parity_mismatches": parity_mismatches,
        "violations": violations.items,
    }
    return summary


def run_pool_soak(seed: int, n_rounds: int = 3, per_round: int = 4) -> dict:
    """The fork phase: 3-worker replica pool under delta.drop/delta.slow/
    replica.crash; every committed write must converge to 200 on fresh
    connections (the resync/respawn machinery is what's under test)."""
    import asyncio

    import httpx

    rng = random.Random(seed + 1)
    FAULTS.reset()
    # armed BEFORE the fork so every replica inherits it: each child
    # crashes applying its first delta, and the supervisor must respawn
    # the whole pool from the zygote (the existing drill in
    # tests/test_faults.py::test_inherited_replica_crash_fault_heals)
    FAULTS.arm("replica.crash")
    cfg = Config(
        values={
            "namespaces": [{"id": 1, "name": "n"}],
            "log": {"level": "error"},
            "serve": {
                "read": {"port": 0, "host": "127.0.0.1", "workers": 3},
                "write": {"port": 0, "host": "127.0.0.1"},
            },
        }
    )
    reg = Registry(cfg)
    loop = asyncio.new_event_loop()
    threading.Thread(target=loop.run_forever, daemon=True).start()
    violations = _Violations()
    injected: list[dict] = []
    t_start = time.perf_counter()
    try:
        rp, wp = asyncio.run_coroutine_threadsafe(
            reg.start_all(), loop
        ).result(timeout=120)
        # parent disarms NOW: respawn commands carry the parent's current
        # snapshot, so replacements come back clean and the pool heals
        FAULTS.disarm("replica.crash")
        injected.append({"round": -1, "site": "replica.crash"})

        def put(obj: str) -> None:
            body = {
                "namespace": "n", "object": obj, "relation": "view",
                "subject_id": "alice",
            }
            r = httpx.put(
                f"http://127.0.0.1:{wp}/relation-tuples",
                json=body, timeout=30,
            )
            if r.status_code != 201:
                violations.add(f"write {obj} failed: {r.status_code}")

        def converges(obj: str, timeout: float = 60.0) -> bool:
            params = {
                "namespace": "n", "object": obj, "relation": "view",
                "subject_id": "alice",
            }
            deadline = time.time() + timeout
            streak = 0
            while streak < 12 and time.time() < deadline:
                try:  # fresh connection per probe: covers every replica
                    r = httpx.get(
                        f"http://127.0.0.1:{rp}/check",
                        params=params, timeout=10,
                    )
                    streak = streak + 1 if r.status_code == 200 else 0
                except httpx.HTTPError:
                    streak = 0
                time.sleep(0.01)
            return streak >= 12

        wrote: list[str] = []
        for rnd in range(n_rounds):
            site = ("delta.drop", "delta.slow")[rng.randrange(2)]
            if site == "delta.slow":
                FAULTS.arm_slow(site, sleep_ms=200)
            else:
                FAULTS.arm(site)
            injected.append({"round": rnd, "site": site})
            for i in range(per_round):
                obj = f"pool{rnd}_{i}"
                put(obj)
                wrote.append(obj)
            FAULTS.reset()  # respawn snapshots must come back clean
            for obj in wrote[-per_round:]:
                if not converges(obj):
                    violations.add(
                        f"{obj} never converged after {site} round"
                    )
        # everything ever written still answers everywhere
        for obj in (wrote[0], wrote[-1]):
            if not converges(obj):
                violations.add(f"{obj} lost after the full soak")
        m = reg.metrics()._metrics
        respawn_count = (
            m["keto_replica_respawns_total"].value
            if "keto_replica_respawns_total" in m
            else 0
        )
        if respawn_count < 1:
            violations.add(
                "inherited replica.crash produced no respawns — the "
                "supervisor/zygote heal path never ran"
            )
        summary = {
            "phase": "pool",
            "seed": seed,
            "writes": len(wrote),
            "wall_s": round(time.perf_counter() - t_start, 2),
            "faults_injected": injected,
            "respawns": respawn_count,
            "resyncs": m["keto_replica_resyncs_total"].value
            if "keto_replica_resyncs_total" in m
            else 0,
            "violations": violations.items,
        }
        return summary
    finally:
        FAULTS.reset()
        try:
            asyncio.run_coroutine_threadsafe(reg.stop_all(), loop).result(
                timeout=30
            )
        except Exception:
            pass
        loop.call_soon_threadsafe(loop.stop)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seed", type=int, default=4)
    ap.add_argument(
        "--smoke", action="store_true",
        help="small deterministic tier for tools/check.sh",
    )
    ap.add_argument("--ops", type=int, default=0, help="reader ops total")
    ap.add_argument("--writes", type=int, default=0)
    ap.add_argument("--faults", type=int, default=0)
    ap.add_argument(
        "--pool", action="store_true",
        help="also run the forked replica-pool phase",
    )
    args = ap.parse_args(argv)

    if args.smoke:
        ops, writes, faults = 800, 80, 5
    else:
        ops, writes, faults = 8000, 600, 24
    if args.ops:
        ops = args.ops
    if args.writes:
        writes = args.writes
    if args.faults:
        faults = args.faults

    phases = [run_engine_soak(args.seed, n_ops=ops, n_writes=writes,
                              n_faults=faults)]
    if args.pool:
        phases.append(run_pool_soak(args.seed))
    bad = [v for p in phases for v in p["violations"]]
    print(json.dumps({"phases": phases, "ok": not bad}, indent=2))
    if bad:
        print(f"SOAK FAILED: {len(bad)} violation(s)", file=sys.stderr)
        for v in bad:
            print(f"  - {v}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
