#!/usr/bin/env python
"""Overload gate (tools/check.sh): the overload-control plane against a
scripted open-loop world with a KNOWN capacity.

The in-process OverloadController fronts a deterministic queueing system
(fixed service capacity, fake clock — no sleeps, no server boot) driven
through three phases: a 1x warmup at capacity, a sustained 10x open-loop
burst, and a 1x recovery. The gate proves the serving invariants the
plane exists for:

- **goodput floor**: during the 10x burst the served rate stays >= 0.8x
  of measured capacity — admission control sheds the excess instead of
  letting a standing queue destroy everyone's latency;
- **strict shed ordering**: ``sheddable`` sheds strictly before the
  first ``default`` shed, and ``critical`` is NEVER shed (the static
  max_queue backstop is sized out of reach here, so any critical shed
  is a ladder bug);
- **bounded accepted latency**: requests the plane admits AND serves
  complete within a small multiple of the standing-queue target — the
  CoDel cull + adaptive LIFO keep accepted work fresh instead of
  serving a minutes-deep queue in order;
- **ladder recovery**: after the burst ends, keto_overload_state steps
  back down to normal within the hysteresis windows (one per rung) —
  no latched brownout;
- **retry discipline**: shed clients retrying through a RetryBudget
  amplify offered load by <= 1.1x (burst tokens excluded), not by
  max_attempts x;
- **evidence**: every ladder transition is a flight-recorder event
  (kind=overload) and the keto_overload_* metric families are present.

Exit 0 = all invariants hold; exit 1 with a reason otherwise.
Sub-second runtime: the cheap always-on CI proof that brownout logic
degrades in priority order and un-degrades when load drops.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from keto_tpu.client.retry import (  # noqa: E402
    RetryBudget,
    RetryPolicy,
    run_with_retry,
)
from keto_tpu.engine.overload import (  # noqa: E402
    CRITICAL,
    DEFAULT,
    SHEDDABLE,
    AdaptiveLimiter,
    AdaptiveThrottle,
    BrownoutController,
    OverloadController,
)
from keto_tpu.telemetry import MetricsRegistry  # noqa: E402
from keto_tpu.telemetry.flight import FlightRecorder  # noqa: E402
from keto_tpu.utils.errors import ErrResourceExhausted  # noqa: E402


def fail(msg: str) -> None:
    print(f"overload gate: FAIL: {msg}")
    sys.exit(1)


class World:
    """Deterministic open-loop queueing system: ``capacity`` requests
    served per simulated second, arrivals offered tick-by-tick at a
    criticality mix of 20% critical / 60% default / 20% sheddable."""

    TICK_S = 0.01

    def __init__(self, controller: OverloadController, capacity: float):
        self.c = controller
        self.capacity = capacity
        self.now = 0.0
        self.queue: list = []  # (t_arrival, criticality)
        self.served = 0
        self.culled = 0
        self.accepted_delays: list = []
        self.shed_log: list = []  # criticality, in shed order

    def mix(self, i: int) -> str:
        r = i % 10
        if r < 2:
            return CRITICAL
        if r < 8:
            return DEFAULT
        return SHEDDABLE

    def tick(self, offered_rate: float) -> None:
        self.now += self.TICK_S
        n_arrivals = int(round(offered_rate * self.TICK_S))
        for i in range(n_arrivals):
            crit = self.mix(self.served + len(self.queue) + i)
            reason = self.c.admit(len(self.queue), crit)
            if reason is None:
                self.queue.append((self.now, crit))
            else:
                self.shed_log.append(crit)
        # queue discipline: the controller's CoDel cull + LIFO flip
        cutoff = self.c.cull_age_s()
        if cutoff is not None:
            keep = [e for e in self.queue if self.now - e[0] <= cutoff]
            n_culled = len(self.queue) - len(keep)
            if n_culled:
                self.c.note_culled(n_culled)
                self.culled += n_culled
                self.queue = keep
        budget = int(round(self.capacity * self.TICK_S))
        if self.c.lifo():
            batch, self.queue = self.queue[-budget:], self.queue[:-budget]
        else:
            batch, self.queue = self.queue[:budget], self.queue[budget:]
        if batch:
            delay = self.now - min(t for t, _ in batch)
            self.c.observe(delay, service_s=self.TICK_S)
            self.served += len(batch)
            self.accepted_delays.extend(self.now - t for t, _ in batch)
        else:
            self.c.observe(0.0)


def main() -> int:
    capacity = 2000.0  # requests per simulated second
    world_ref = {}
    clock = lambda: world_ref["w"].now  # noqa: E731
    flight = FlightRecorder(capacity=512, clock=clock)
    metrics = MetricsRegistry()
    target_s = 0.05
    controller = OverloadController(
        max_queue=1_000_000,  # backstop sized out of reach: ladder only
        limiter=AdaptiveLimiter(
            initial=200, min_limit=8, max_limit=1_000_000,
            target_delay_s=target_s, interval_s=0.1, clock=clock,
        ),
        brownout=BrownoutController(
            hysteresis_s=0.5, min_dwell_s=0.05, flight=flight, clock=clock,
        ),
        throttle=AdaptiveThrottle(window_s=5.0, clock=clock),
        metrics=metrics,
        flight=flight,
        clock=clock,
        rand=lambda: 0.5,
    )
    world = World(controller, capacity)
    world_ref["w"] = world

    # -- phase 1: 1x warmup (2 simulated seconds) ----------------------------
    for _ in range(200):
        world.tick(capacity)
    if controller.state() != 0:
        fail(
            f"ladder left normal under 1x load "
            f"(state={controller.snapshot()['state_name']})"
        )
    sheds_at_capacity = len(world.shed_log)

    # -- phase 2: 10x open-loop burst (4 simulated seconds) ------------------
    served_before = world.served
    burst_ticks = 400
    for _ in range(burst_ticks):
        world.tick(10.0 * capacity)
    burst_goodput = (world.served - served_before) / (
        burst_ticks * World.TICK_S
    )
    snap = controller.snapshot()

    if burst_goodput < 0.8 * capacity:
        fail(
            f"goodput under 10x burst was {burst_goodput:.0f}/s, below "
            f"the 0.8x floor of capacity {capacity:.0f}/s"
        )
    sheds = snap["sheds_by_class"]
    if sheds[CRITICAL] != 0:
        fail(f"{sheds[CRITICAL]} critical requests shed — ladder must "
             "never shed critical before the hard backstop")
    if sheds[SHEDDABLE] == 0:
        fail("a 10x burst shed nothing sheddable — admission is dead")
    burst_sheds = world.shed_log[sheds_at_capacity:]
    if DEFAULT in burst_sheds:
        first_default = burst_sheds.index(DEFAULT)
        if SHEDDABLE not in burst_sheds[:first_default]:
            fail("a default-class request was shed before any "
                 "sheddable-class request — brownout ordering violated")
    if snap["state"] < 3:
        fail(
            f"10x burst never climbed the ladder to shed_sheddable "
            f"(state={snap['state_name']})"
        )

    # accepted-work latency stays bounded: CoDel cull + LIFO mean admitted
    # requests are served fresh, not after a minutes-deep queue drains
    worst_accepted = max(world.accepted_delays)
    if worst_accepted > 20 * target_s:
        fail(
            f"an admitted request waited {worst_accepted * 1e3:.0f}ms, "
            f"over 20x the {target_s * 1e3:.0f}ms standing-queue target "
            "— the cull/LIFO discipline is not bounding accepted latency"
        )

    # -- phase 3: 1x recovery — ladder must step back down -------------------
    # one hysteresis window per rung (+1 slack for the dwell)
    recovery_ticks = int((snap["state"] + 1) * 0.5 / World.TICK_S) + 100
    for _ in range(recovery_ticks):
        world.tick(capacity)
    if controller.state() != 0:
        fail(
            f"ladder did not return to normal within "
            f"{recovery_ticks * World.TICK_S:.1f}s of the burst ending "
            f"(state={controller.snapshot()['state_name']})"
        )

    # -- evidence: flight transitions + metric families ----------------------
    kinds = [r for r in flight.records() if r.get("kind") == "overload"]
    if not kinds:
        fail("no kind=overload flight records — transitions are invisible")
    directions = {r.get("direction") for r in kinds}
    if not {"up", "down"} <= directions:
        fail(f"flight records cover directions {directions}, need both "
             "up and down")
    text = metrics.expose()
    for family in (
        "keto_overload_state",
        "keto_overload_limit",
        "keto_overload_sheds_total",
        "keto_overload_transitions_total",
    ):
        if family not in text:
            fail(f"metric family {family} missing from exposition")

    # -- retry discipline: budget caps amplification at ~1.1x ----------------
    budget = RetryBudget(ratio=0.1, burst=10.0)
    policy = RetryPolicy(
        max_attempts=4, base_delay_s=0.0, max_delay_s=0.0,
        sleep=lambda _s: None, rand=lambda: 0.0,
    )
    attempts = [0]

    def always_shed(_remaining):
        attempts[0] += 1
        raise ErrResourceExhausted("scripted shed")

    n_requests = 2000
    for _ in range(n_requests):
        try:
            run_with_retry(
                always_shed, policy,
                retryable=lambda e: isinstance(e, ErrResourceExhausted),
                budget=budget,
            )
        except ErrResourceExhausted:
            pass
    amplification = (attempts[0] - budget.burst) / n_requests
    if amplification > 1.1:
        fail(
            f"retry amplification under total shed was "
            f"{amplification:.3f}x, over the 1.1x budget ceiling"
        )

    print(
        f"overload gate: OK — goodput {burst_goodput:.0f}/s "
        f"(>= 0.8x of {capacity:.0f}/s) at 10x, sheds "
        f"crit/def/shed={sheds[CRITICAL]}/{sheds[DEFAULT]}/"
        f"{sheds[SHEDDABLE]} in priority order, worst accepted delay "
        f"{worst_accepted * 1e3:.0f}ms, ladder recovered to normal, "
        f"{len(kinds)} flight transitions, retry amplification "
        f"{amplification:.3f}x"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
