#!/usr/bin/env python
"""Strict Prometheus / OpenMetrics text-format linter, plus a live-daemon
gate: boot the full serving stack on loopback ports, drive traffic through
both transports, scrape /metrics from both planes in both formats, and
fail on any naming-convention, duplicate-series, or format violation.

The linter is importable (``lint_text(text, openmetrics=False)``) so
tests can round-trip expositions through it; ``main()`` is the
tools/check.sh tier.

Checks enforced per family / series:
- family names are lowercase snake_case with the ``keto_``/``process_``
  style prefix shape ``^[a-z][a-z0-9_]*$``
- every sample belongs to a family that declared # HELP and # TYPE first,
  and each family declares them exactly once
- counter families end in ``_total``; counter/gauge sample names equal
  the family name; histogram samples are only ``_bucket``/``_sum``/
  ``_count``
- histogram ``le`` buckets are cumulative (non-decreasing counts in
  increasing le order), include ``+Inf``, and the +Inf count equals
  ``_count``
- no duplicate series (same sample name + identical label set twice)
- label names match ``^[a-zA-Z_][a-zA-Z0-9_]*$``; label values use only
  the legal escapes (\\\\, \\", \\n); sample values parse as floats
- exemplars (``# {...} value ts``) appear only in OpenMetrics mode and
  only on ``_bucket`` lines; OpenMetrics expositions end with ``# EOF``

Usage:
    python tools/lint_metrics.py            # live-daemon gate (check.sh)
    python tools/lint_metrics.py --file X   # lint a saved exposition
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

_FAMILY_RE = re.compile(r"^[a-z][a-z0-9_]*$")
_LABEL_NAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")
# a sample line: name{labels} value [# {exemplar-labels} value [ts]]
_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})?"
    r" (?P<value>\S+)"
    r"(?P<exemplar> # \{[^}]*\} \S+(?: \S+)?)?$"
)
_ESCAPE_RE = re.compile(r"\\(.)")
_LEGAL_ESCAPES = {"\\", '"', "n"}

_HIST_SUFFIXES = ("_bucket", "_sum", "_count")


def _parse_labels(raw: str):
    """'a="x",b="y"' -> dict, or a string error."""
    labels = {}
    rest = raw
    while rest:
        m = re.match(r'([a-zA-Z_][a-zA-Z0-9_]*)="', rest)
        if m is None:
            return f"malformed label segment {rest!r}"
        name = m.group(1)
        i = m.end()
        value_chars = []
        while i < len(rest):
            c = rest[i]
            if c == "\\":
                if i + 1 >= len(rest):
                    return f"dangling escape in label {name}"
                esc = rest[i + 1]
                if esc not in _LEGAL_ESCAPES:
                    return f"illegal escape \\{esc} in label {name}"
                value_chars.append(c + esc)
                i += 2
                continue
            if c == '"':
                break
            value_chars.append(c)
            i += 1
        else:
            return f"unterminated label value for {name}"
        if name in labels:
            return f"duplicate label name {name}"
        labels[name] = "".join(value_chars)
        rest = rest[i + 1:]
        if rest.startswith(","):
            rest = rest[1:]
        elif rest:
            return f"expected ',' between labels, got {rest!r}"
    return labels


def _family_of(sample_name: str, families: dict) -> str | None:
    """Longest declared family this sample name could belong to."""
    if sample_name in families:
        return sample_name
    for suffix in _HIST_SUFFIXES:
        if sample_name.endswith(suffix) and sample_name[: -len(suffix)] in families:
            return sample_name[: -len(suffix)]
    return None


def _le_sort_key(le: str) -> float:
    if le == "+Inf":
        return float("inf")
    try:
        return float(le)
    except ValueError:
        return float("nan")


def lint_text(text: str, openmetrics: bool = False) -> list[str]:
    """Return a list of human-readable violations (empty = clean)."""
    violations: list[str] = []
    families: dict[str, dict] = {}  # name -> {help, type, samples}
    seen_series: set[tuple] = set()
    # family -> {label-key-without-le: [(le, count)]}
    buckets: dict[str, dict[tuple, list]] = {}
    counts: dict[str, dict[tuple, float]] = {}
    lines = text.split("\n")
    if lines and lines[-1] == "":
        lines.pop()
    saw_eof = False
    for lineno, line in enumerate(lines, start=1):
        if saw_eof:
            violations.append(f"line {lineno}: content after # EOF")
            break
        if line == "# EOF":
            if not openmetrics:
                violations.append(
                    f"line {lineno}: # EOF in a non-OpenMetrics exposition"
                )
            saw_eof = True
            continue
        if line.startswith("# HELP ") or line.startswith("# TYPE "):
            kind = line[2:6]
            rest = line[7:]
            parts = rest.split(" ", 1)
            name = parts[0]
            payload = parts[1] if len(parts) > 1 else ""
            if not _FAMILY_RE.match(name):
                violations.append(
                    f"line {lineno}: family name {name!r} violates "
                    "lowercase snake_case convention"
                )
            fam = families.setdefault(
                name, {"help": None, "type": None, "samples": 0}
            )
            if kind == "HELP":
                if fam["help"] is not None:
                    violations.append(
                        f"line {lineno}: duplicate # HELP for {name}"
                    )
                fam["help"] = payload
            else:
                if fam["type"] is not None:
                    violations.append(
                        f"line {lineno}: duplicate # TYPE for {name}"
                    )
                if payload not in ("counter", "gauge", "histogram", "summary"):
                    violations.append(
                        f"line {lineno}: unknown TYPE {payload!r} for {name}"
                    )
                if fam["samples"]:
                    violations.append(
                        f"line {lineno}: # TYPE for {name} after its samples"
                    )
                fam["type"] = payload
            continue
        if line.startswith("#"):
            continue  # free-form comment
        if not line.strip():
            violations.append(f"line {lineno}: blank line in exposition")
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            violations.append(f"line {lineno}: unparseable sample {line!r}")
            continue
        name = m.group("name")
        raw_labels = m.group("labels")
        labels = _parse_labels(raw_labels) if raw_labels else {}
        if isinstance(labels, str):
            violations.append(f"line {lineno}: {labels}")
            continue
        for ln in labels:
            if not _LABEL_NAME_RE.match(ln):
                violations.append(
                    f"line {lineno}: illegal label name {ln!r}"
                )
        try:
            value = float(m.group("value"))
        except ValueError:
            violations.append(
                f"line {lineno}: non-numeric value {m.group('value')!r}"
            )
            continue
        if m.group("exemplar"):
            if not openmetrics:
                violations.append(
                    f"line {lineno}: exemplar in a non-OpenMetrics exposition"
                )
            elif not name.endswith("_bucket"):
                violations.append(
                    f"line {lineno}: exemplar on non-bucket sample {name}"
                )
        fam_name = _family_of(name, families)
        if fam_name is None:
            violations.append(
                f"line {lineno}: sample {name} has no preceding "
                "# HELP/# TYPE family declaration"
            )
            continue
        fam = families[fam_name]
        fam["samples"] += 1
        if fam["help"] is None:
            violations.append(f"line {lineno}: {fam_name} missing # HELP")
        if fam["type"] is None:
            violations.append(f"line {lineno}: {fam_name} missing # TYPE")
        ftype = fam["type"]
        if ftype == "counter":
            if not fam_name.endswith("_total"):
                violations.append(
                    f"counter family {fam_name} does not end in _total"
                )
            if name != fam_name:
                violations.append(
                    f"line {lineno}: counter sample {name} != family "
                    f"{fam_name}"
                )
            if value < 0:
                violations.append(
                    f"line {lineno}: negative counter {name} = {value}"
                )
        elif ftype == "gauge":
            if name != fam_name:
                violations.append(
                    f"line {lineno}: gauge sample {name} != family {fam_name}"
                )
        elif ftype == "histogram":
            suffix = name[len(fam_name):]
            if suffix not in _HIST_SUFFIXES:
                violations.append(
                    f"line {lineno}: histogram sample suffix {suffix!r} "
                    f"on {fam_name}"
                )
            if suffix == "_bucket":
                if "le" not in labels:
                    violations.append(
                        f"line {lineno}: _bucket sample without le label"
                    )
                else:
                    key = tuple(
                        sorted(
                            (k, v) for k, v in labels.items() if k != "le"
                        )
                    )
                    buckets.setdefault(fam_name, {}).setdefault(
                        key, []
                    ).append((labels["le"], value))
            elif suffix == "_count":
                key = tuple(sorted(labels.items()))
                counts.setdefault(fam_name, {})[key] = value
        series_key = (name, tuple(sorted(labels.items())))
        if series_key in seen_series:
            violations.append(
                f"line {lineno}: duplicate series {name}"
                f"{dict(sorted(labels.items()))}"
            )
        seen_series.add(series_key)
    if openmetrics and not saw_eof:
        violations.append("OpenMetrics exposition missing trailing # EOF")
    # NOTE: a family with # HELP/# TYPE and zero samples is legal — labeled
    # metrics expose headers before their first child is created.
    # bucket monotonicity + +Inf/_count agreement
    for fam_name, by_series in buckets.items():
        for key, pairs in by_series.items():
            ordered = sorted(pairs, key=lambda p: _le_sort_key(p[0]))
            les = [p[0] for p in ordered]
            vals = [p[1] for p in ordered]
            if any(v != v for v in (_le_sort_key(le) for le in les)):
                violations.append(
                    f"{fam_name}{dict(key)}: unparseable le value in {les}"
                )
                continue
            if "+Inf" not in les:
                violations.append(
                    f"{fam_name}{dict(key)}: no +Inf bucket"
                )
            if any(b < a for a, b in zip(vals, vals[1:])):
                violations.append(
                    f"{fam_name}{dict(key)}: bucket counts not cumulative "
                    f"({vals})"
                )
            cnt = counts.get(fam_name, {}).get(key)
            if cnt is not None and les and les[-1] == "+Inf" and vals[-1] != cnt:
                violations.append(
                    f"{fam_name}{dict(key)}: +Inf bucket {vals[-1]} != "
                    f"_count {cnt}"
                )
    return violations


# -- live-daemon gate ---------------------------------------------------------


def _scrape(port: int, openmetrics: bool) -> str:
    import urllib.request

    req = urllib.request.Request(f"http://127.0.0.1:{port}/metrics")
    if openmetrics:
        req.add_header("Accept", "application/openmetrics-text")
    with urllib.request.urlopen(req, timeout=10) as resp:
        ctype = resp.headers.get("Content-Type", "")
        body = resp.read().decode("utf-8")
    if openmetrics and "application/openmetrics-text" not in ctype:
        raise RuntimeError(
            f"OpenMetrics scrape answered Content-Type {ctype!r}"
        )
    return body


def _run_live_gate() -> list[str]:
    """Boot the serving stack, drive both transports, lint every
    plane/format combination."""
    import asyncio
    import threading
    import urllib.request

    from keto_tpu.driver.config import Config
    from keto_tpu.driver.registry import Registry

    cfg = Config(
        values={
            "namespaces": [{"id": 1, "name": "lintns"}],
            "serve": {
                "read": {"port": 0, "host": "127.0.0.1"},
                "write": {"port": 0, "host": "127.0.0.1"},
            },
            "log": {"level": "error", "format": "json"},
            "tracing": {"provider": ""},
        },
        env={},
    )
    registry = Registry(cfg)
    loop = asyncio.new_event_loop()
    threading.Thread(target=loop.run_forever, daemon=True).start()
    read_port, write_port = asyncio.run_coroutine_threadsafe(
        registry.start_all(), loop
    ).result(timeout=180)
    violations: list[str] = []
    try:
        # traffic: a write, an allowed check, a denied check, a batch —
        # populates the request/check/pipeline series on both planes
        body = json.dumps(
            {
                "namespace": "lintns",
                "object": "doc",
                "relation": "view",
                "subject_id": "alice",
            }
        ).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{write_port}/relation-tuples",
            data=body,
            method="PUT",
            headers={"Content-Type": "application/json"},
        )
        urllib.request.urlopen(req, timeout=10).read()
        for subject in ("alice", "mallory"):
            try:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{read_port}/check?namespace=lintns"
                    f"&object=doc&relation=view&subject_id={subject}",
                    timeout=10,
                ).read()
            except urllib.error.HTTPError as e:
                if e.code != 403:
                    raise
        batch = json.dumps(
            {
                "tuples": [
                    {
                        "namespace": "lintns",
                        "object": "doc",
                        "relation": "view",
                        "subject_id": "alice",
                    }
                ]
            }
        ).encode()
        urllib.request.urlopen(
            urllib.request.Request(
                f"http://127.0.0.1:{read_port}/check/batch",
                data=batch,
                headers={"Content-Type": "application/json"},
            ),
            timeout=10,
        ).read()
        for plane, port in (("read", read_port), ("write", write_port)):
            for om in (False, True):
                label = f"{plane}/{'openmetrics' if om else 'text'}"
                try:
                    text = _scrape(port, om)
                except Exception as e:
                    violations.append(f"{label}: scrape failed: {e}")
                    continue
                violations.extend(
                    f"{label}: {v}" for v in lint_text(text, openmetrics=om)
                )
    finally:
        asyncio.run_coroutine_threadsafe(
            registry.stop_all(), loop
        ).result(timeout=60)
        loop.call_soon_threadsafe(loop.stop)
    return violations


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--file", default=None,
        help="lint a saved exposition instead of booting a live daemon",
    )
    ap.add_argument(
        "--openmetrics", action="store_true",
        help="treat --file input as OpenMetrics (exemplars + # EOF)",
    )
    args = ap.parse_args()
    if args.file:
        with open(args.file) as f:
            violations = lint_text(f.read(), openmetrics=args.openmetrics)
    else:
        violations = _run_live_gate()
    if violations:
        for v in violations:
            print(f"VIOLATION: {v}", file=sys.stderr)
        print(
            json.dumps({"metrics_lint": "fail", "violations": len(violations)})
        )
        return 1
    print(json.dumps({"metrics_lint": "ok"}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
