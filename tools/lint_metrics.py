#!/usr/bin/env python
"""Strict Prometheus / OpenMetrics text-format linter, plus a live-daemon
gate: boot the full serving stack on loopback ports, drive traffic through
both transports, scrape /metrics from both planes in both formats, and
fail on any naming-convention, duplicate-series, or format violation.

The exposition PARSER lives in keto_tpu/telemetry/openmetrics.py
(``parse_text``) so the cluster federation scraper reads member
expositions through exactly the grammar this linter enforces; this module
layers the semantic conventions on top and re-exports ``parse_text`` for
callers that imported it from here.

The linter is importable (``lint_text(text, openmetrics=False)``) so
tests can round-trip expositions through it; ``main()`` is the
tools/check.sh tier.

Checks enforced per family / series:
- family names are lowercase snake_case with the ``keto_``/``process_``
  style prefix shape ``^[a-z][a-z0-9_]*$``
- every sample belongs to a family that declared # HELP and # TYPE first,
  and each family declares them exactly once
- counter families end in ``_total``; counter/gauge sample names equal
  the family name; histogram samples are only ``_bucket``/``_sum``/
  ``_count``
- histogram ``le`` buckets are cumulative (non-decreasing counts in
  increasing le order), include ``+Inf``, and the +Inf count equals
  ``_count``
- no duplicate series (same sample name + identical label set twice)
- label names match ``^[a-zA-Z_][a-zA-Z0-9_]*$``; label values use only
  the legal escapes (\\\\, \\", \\n); sample values parse as floats
- exemplars (``# {...} value ts``) appear only in OpenMetrics mode and
  only on ``_bucket`` lines; OpenMetrics expositions end with ``# EOF``

The live gate additionally boots the node with cluster self-federation
enabled, so the leader-side ``keto_cluster_*`` instance-labeled series
pass the same both-planes / both-formats lint as everything else.

Usage:
    python tools/lint_metrics.py            # live-daemon gate (check.sh)
    python tools/lint_metrics.py --file X   # lint a saved exposition
"""

from __future__ import annotations

import argparse
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from keto_tpu.telemetry.openmetrics import (  # noqa: E402
    HIST_SUFFIXES,
    parse_text,
)

__all__ = ["lint_text", "parse_text"]


def _le_sort_key(le: str) -> float:
    if le == "+Inf":
        return float("inf")
    try:
        return float(le)
    except ValueError:
        return float("nan")


def lint_text(text: str, openmetrics: bool = False) -> list[str]:
    """Return a list of human-readable violations (empty = clean)."""
    parsed = parse_text(text, openmetrics=openmetrics)
    violations: list[str] = list(parsed.errors)
    # family -> {label-key-without-le: [(le, count)]}
    buckets: dict[str, dict[tuple, list]] = {}
    counts: dict[str, dict[tuple, float]] = {}
    for fam in parsed.families.values():
        ftype = fam.type
        if ftype == "counter" and not fam.name.endswith("_total"):
            violations.append(
                f"counter family {fam.name} does not end in _total"
            )
        for s in fam.samples:
            if ftype == "counter":
                if s.name != fam.name:
                    violations.append(
                        f"line {s.lineno}: counter sample {s.name} != "
                        f"family {fam.name}"
                    )
                if s.value < 0:
                    violations.append(
                        f"line {s.lineno}: negative counter {s.name} = "
                        f"{s.value}"
                    )
            elif ftype == "gauge":
                if s.name != fam.name:
                    violations.append(
                        f"line {s.lineno}: gauge sample {s.name} != "
                        f"family {fam.name}"
                    )
            elif ftype == "histogram":
                suffix = s.name[len(fam.name):]
                if suffix not in HIST_SUFFIXES:
                    violations.append(
                        f"line {s.lineno}: histogram sample suffix "
                        f"{suffix!r} on {fam.name}"
                    )
                if suffix == "_bucket":
                    if "le" not in s.labels:
                        violations.append(
                            f"line {s.lineno}: _bucket sample without "
                            "le label"
                        )
                    else:
                        key = tuple(
                            sorted(
                                (k, v)
                                for k, v in s.labels.items()
                                if k != "le"
                            )
                        )
                        buckets.setdefault(fam.name, {}).setdefault(
                            key, []
                        ).append((s.labels["le"], s.value))
                elif suffix == "_count":
                    key = tuple(sorted(s.labels.items()))
                    counts.setdefault(fam.name, {})[key] = s.value
    # NOTE: a family with # HELP/# TYPE and zero samples is legal — labeled
    # metrics expose headers before their first child is created.
    # bucket monotonicity + +Inf/_count agreement
    for fam_name, by_series in buckets.items():
        for key, pairs in by_series.items():
            ordered = sorted(pairs, key=lambda p: _le_sort_key(p[0]))
            les = [p[0] for p in ordered]
            vals = [p[1] for p in ordered]
            if any(v != v for v in (_le_sort_key(le) for le in les)):
                violations.append(
                    f"{fam_name}{dict(key)}: unparseable le value in {les}"
                )
                continue
            if "+Inf" not in les:
                violations.append(
                    f"{fam_name}{dict(key)}: no +Inf bucket"
                )
            if any(b < a for a, b in zip(vals, vals[1:])):
                violations.append(
                    f"{fam_name}{dict(key)}: bucket counts not cumulative "
                    f"({vals})"
                )
            cnt = counts.get(fam_name, {}).get(key)
            if cnt is not None and les and les[-1] == "+Inf" and vals[-1] != cnt:
                violations.append(
                    f"{fam_name}{dict(key)}: +Inf bucket {vals[-1]} != "
                    f"_count {cnt}"
                )
    return violations


# -- live-daemon gate ---------------------------------------------------------


def _scrape(port: int, openmetrics: bool) -> str:
    import urllib.request

    req = urllib.request.Request(f"http://127.0.0.1:{port}/metrics")
    if openmetrics:
        req.add_header("Accept", "application/openmetrics-text")
    with urllib.request.urlopen(req, timeout=10) as resp:
        ctype = resp.headers.get("Content-Type", "")
        body = resp.read().decode("utf-8")
    if openmetrics and "application/openmetrics-text" not in ctype:
        raise RuntimeError(
            f"OpenMetrics scrape answered Content-Type {ctype!r}"
        )
    return body


def _run_live_gate() -> list[str]:
    """Boot the serving stack (with cluster self-federation on, so the
    federated keto_cluster_* series are part of the exposition under
    test), drive both transports, lint every plane/format combination."""
    import asyncio
    import threading
    import time
    import urllib.request

    from keto_tpu.driver.config import Config
    from keto_tpu.driver.registry import Registry

    cfg = Config(
        values={
            "namespaces": [{"id": 1, "name": "lintns"}],
            "serve": {
                "read": {"port": 0, "host": "127.0.0.1"},
                "write": {"port": 0, "host": "127.0.0.1"},
            },
            "log": {"level": "error", "format": "json"},
            "tracing": {"provider": ""},
            # self-federation: this standalone node acts as its own
            # one-member cluster, so the leader's federated /metrics
            # (instance-labeled keto_cluster_*) is linted too
            "cluster": {
                "enabled": True,
                "instance_id": "lint-local",
                "scrape_interval_ms": 200,
                "heartbeat_interval_ms": 200,
            },
            # autotuner on (long interval: it must register its
            # keto_autotune_* families for the lint, not actually move
            # knobs mid-scrape)
            "autotune": {"enabled": True, "interval_s": 600.0},
            # scrubber on (same long-interval trick: registers the
            # keto_scrub_* families without scrubbing mid-scrape)
            "scrub": {"enabled": True, "interval_s": 600.0},
            # overload controller on: registers the keto_overload_*
            # families (it only sheds under pressure, so the lint
            # traffic is unaffected)
            "overload": {"enabled": True},
        },
        env={},
    )
    registry = Registry(cfg)
    loop = asyncio.new_event_loop()
    threading.Thread(target=loop.run_forever, daemon=True).start()
    read_port, write_port = asyncio.run_coroutine_threadsafe(
        registry.start_all(), loop
    ).result(timeout=180)
    violations: list[str] = []
    try:
        # traffic: a write, an allowed check, a denied check, a batch —
        # populates the request/check/pipeline series on both planes
        body = json.dumps(
            {
                "namespace": "lintns",
                "object": "doc",
                "relation": "view",
                "subject_id": "alice",
            }
        ).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{write_port}/relation-tuples",
            data=body,
            method="PUT",
            headers={"Content-Type": "application/json"},
        )
        urllib.request.urlopen(req, timeout=10).read()
        for subject in ("alice", "mallory"):
            try:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{read_port}/check?namespace=lintns"
                    f"&object=doc&relation=view&subject_id={subject}",
                    timeout=10,
                ).read()
            except urllib.error.HTTPError as e:
                if e.code != 403:
                    raise
        batch = json.dumps(
            {
                "tuples": [
                    {
                        "namespace": "lintns",
                        "object": "doc",
                        "relation": "view",
                        "subject_id": "alice",
                    }
                ]
            }
        ).encode()
        urllib.request.urlopen(
            urllib.request.Request(
                f"http://127.0.0.1:{read_port}/check/batch",
                data=batch,
                headers={"Content-Type": "application/json"},
            ),
            timeout=10,
        ).read()
        # wait for at least one federation scrape cycle to land, so the
        # keto_cluster_* series exist before the lint pass
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            text = _scrape(read_port, False)
            if 'keto_cluster_member_up{instance="lint-local"}' in text:
                break
            time.sleep(0.2)
        else:
            violations.append(
                "federation: keto_cluster_member_up{instance=\"lint-local\"} "
                "never appeared on /metrics (self-scrape loop not running?)"
            )
        for plane, port in (("read", read_port), ("write", write_port)):
            for om in (False, True):
                label = f"{plane}/{'openmetrics' if om else 'text'}"
                try:
                    text = _scrape(port, om)
                except Exception as e:
                    violations.append(f"{label}: scrape failed: {e}")
                    continue
                violations.extend(
                    f"{label}: {v}" for v in lint_text(text, openmetrics=om)
                )
    finally:
        asyncio.run_coroutine_threadsafe(
            registry.stop_all(), loop
        ).result(timeout=60)
        loop.call_soon_threadsafe(loop.stop)
    return violations


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--file", default=None,
        help="lint a saved exposition instead of booting a live daemon",
    )
    ap.add_argument(
        "--openmetrics", action="store_true",
        help="treat --file input as OpenMetrics (exemplars + # EOF)",
    )
    args = ap.parse_args()
    if args.file:
        with open(args.file) as f:
            violations = lint_text(f.read(), openmetrics=args.openmetrics)
    else:
        violations = _run_live_gate()
    if violations:
        for v in violations:
            print(f"VIOLATION: {v}", file=sys.stderr)
        print(
            json.dumps({"metrics_lint": "fail", "violations": len(violations)})
        )
        return 1
    print(json.dumps({"metrics_lint": "ok"}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
