#!/usr/bin/env python3
"""Import every keto_tpu module and fail fast on any ImportError.

Moved/renamed upstream APIs (the `jax.shard_map` -> `jax.experimental.
shard_map` relocation that silently broke collection of two sharded test
modules) only surface when the module is actually imported — and modules
imported lazily (inside functions, behind config flags) can hide breakage
past the whole test suite. This walks the package tree and imports
everything, so a stale import is one cheap CI step instead of an
in-production surprise.

Exit status: 0 when every module imports, 1 otherwise (each failure is
listed with its originating exception). Modules whose dependencies are
legitimately absent in a build (optional extras) should guard the import
themselves — that is the contract this script enforces.

Usage: JAX_PLATFORMS=cpu python tools/verify_imports.py [package]
"""

from __future__ import annotations

import importlib
import os
import pkgutil
import sys
import traceback

# runnable from anywhere: the repo root is this script's parent dir
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def verify(package_name: str = "keto_tpu") -> int:
    root = importlib.import_module(package_name)
    failures: list[tuple[str, str]] = []
    count = 1
    for info in pkgutil.walk_packages(root.__path__, prefix=f"{package_name}."):
        count += 1
        try:
            importlib.import_module(info.name)
        except BaseException:
            failures.append((info.name, traceback.format_exc()))
    if failures:
        for name, tb in failures:
            print(f"FAIL {name}\n{tb}", file=sys.stderr)
        print(
            f"{len(failures)}/{count} modules failed to import",
            file=sys.stderr,
        )
        return 1
    print(f"ok: {count} modules import cleanly")
    return 0


if __name__ == "__main__":
    sys.exit(verify(sys.argv[1] if len(sys.argv) > 1 else "keto_tpu"))
