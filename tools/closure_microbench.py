"""Closure-build microbench: full semiring rebuild vs incremental update,
plus the reverse-index rungs.

The point of the incremental closure path (keto_tpu.engine.semiring) is that
a small interior edge delta costs proportional to its blast radius, not the
graph. This tool measures exactly that claim at a serving-realistic scale
(m ~ 2048 interior nodes, 3m edges, k_max 4) and — with ``--gate`` — fails
the build when the incremental update after ONE inserted edge is not at
least 5x faster than a full rebuild (median of several trials each).

Two reverse-index rungs ride along (PR 17):

- ``reverse``: maintaining the transposed closure D^T through a 1-edge
  delta (``update_transpose`` over the dirty rows the bitset update
  already computed) must be >= 5x faster than re-transposing D from
  scratch (``transpose_closure``) — the claim that makes carrying D^T
  through incremental builds worthwhile.
- ``list``: answering ``list_objects`` from the reverse residency
  (engine/listing.py) must be >= 10x faster than the brute-force oracle —
  one check per candidate object — on an rbac1m-shaped graph
  (users ∈ groups ∈ roles -> per-resource view grants; scale via
  LIST_BENCH_*; the oracle side is timed over a sample of candidates and
  extrapolated so the gate stays fast). The ratio GROWS with object
  count, so passing at gate scale is conservative for rbac1m proper.

The closure/reverse rungs are pure host numpy; the list rung builds a real
ClosureCheckEngine pinned to JAX_PLATFORMS=cpu, so none of the gates
depend on which accelerator CI got.

Usage:
    python tools/closure_microbench.py            # print JSON numbers
    python tools/closure_microbench.py --gate     # exit 1 on regression
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from keto_tpu.engine.semiring import (  # noqa: E402
    build_closure_bitset,
    transpose_closure,
    update_closure_bitset,
    update_closure_bitset_ex,
    update_transpose,
)

M = int(os.environ.get("CLOSURE_BENCH_M", 2048))
EDGES = int(os.environ.get("CLOSURE_BENCH_EDGES", 3 * M))
K_MAX = int(os.environ.get("CLOSURE_BENCH_KMAX", 4))
TRIALS = int(os.environ.get("CLOSURE_BENCH_TRIALS", 5))
MIN_SPEEDUP = float(os.environ.get("CLOSURE_BENCH_MIN_SPEEDUP", 5.0))
MIN_REVERSE_SPEEDUP = float(
    os.environ.get("CLOSURE_BENCH_MIN_REVERSE_SPEEDUP", 5.0)
)
MIN_LIST_SPEEDUP = float(os.environ.get("LIST_BENCH_MIN_SPEEDUP", 10.0))
LIST_USERS = int(os.environ.get("LIST_BENCH_USERS", 300))
LIST_GROUPS = int(os.environ.get("LIST_BENCH_GROUPS", 24))
LIST_ROLES = int(os.environ.get("LIST_BENCH_ROLES", 8))
LIST_RESOURCES = int(os.environ.get("LIST_BENCH_RESOURCES", 3000))
LIST_ORACLE_SAMPLE = int(os.environ.get("LIST_BENCH_ORACLE_SAMPLE", 200))


def _m_pad(m: int) -> int:
    return ((m + 255) // 256) * 256


def _reverse_rung(d, src, dst, rng) -> dict:
    """Incremental D^T maintenance vs full re-transpose on a 1-edge delta.

    The closure update itself runs either way; what the reverse rung
    isolates is the choice AFTER it — re-gather only the dirty columns of
    D^T (update_transpose) or rebuild the whole transpose."""
    m_pad = _m_pad(M)
    d_rev = transpose_closure(d)
    full_s = []
    for _ in range(TRIALS):
        t0 = time.perf_counter()
        transpose_closure(d)
        full_s.append(time.perf_counter() - t0)
    incr_s = []
    dirty = 0
    for i in range(TRIALS):
        e_src = np.concatenate([src, [np.int32((29 * i + 5) % M)]])
        e_dst = np.concatenate([dst, [np.int32((53 * i + 11) % M)]])
        d_new, rows = update_closure_bitset_ex(
            d, src, dst, e_src, e_dst, M, m_pad, K_MAX
        )
        dirty = max(dirty, int(rows.size))
        t0 = time.perf_counter()
        update_transpose(d_rev, d_new, rows)
        incr_s.append(time.perf_counter() - t0)
    full_med = float(np.median(full_s))
    incr_med = float(np.median(incr_s))
    return {
        "full_transpose_median_s": round(full_med, 6),
        "incremental_median_s": round(incr_med, 6),
        "dirty_rows_max": dirty,
        "speedup": round(
            full_med / incr_med if incr_med > 0 else float("inf"), 2
        ),
    }


def _list_rung(rng) -> dict:
    """list_objects via the reverse residency vs the brute-force oracle
    (one fallback check per candidate object) on an rbac-shaped graph.
    The oracle side times LIST_ORACLE_SAMPLE candidates and extrapolates
    linearly — per-candidate cost is flat across same-shaped checks."""
    from keto_tpu.engine.closure import ClosureCheckEngine
    from keto_tpu.engine.listing import ListEngine
    from keto_tpu.graph.snapshot import SnapshotManager
    from keto_tpu.relationtuple.definitions import (
        RelationQuery,
        RelationTuple,
        SubjectID,
        SubjectSet,
    )
    from keto_tpu.store.memory import InMemoryTupleStore
    from keto_tpu.utils.pagination import PaginationOptions

    tuples = []
    for u in range(LIST_USERS):
        for g in rng.choice(LIST_GROUPS, 2, replace=False):
            tuples.append(
                RelationTuple("rbac", f"g{g}", "member", SubjectID(f"u{u}"))
            )
    for g in range(LIST_GROUPS):
        for r in rng.choice(LIST_ROLES, 2, replace=False):
            tuples.append(
                RelationTuple(
                    "rbac", f"role{r}", "member",
                    SubjectSet("rbac", f"g{g}", "member"),
                )
            )
    for res in range(LIST_RESOURCES):
        r = int(rng.integers(0, LIST_ROLES))
        tuples.append(
            RelationTuple(
                "rbac", f"res{res}", "view",
                SubjectSet("rbac", f"role{r}", "member"),
            )
        )
    store = InMemoryTupleStore()
    store.write_relation_tuples(*tuples)

    t0 = time.perf_counter()
    eng = ClosureCheckEngine(
        SnapshotManager(store), max_depth=5, freshness="strong",
        rebuild_debounce_s=0.0, query_mode="host",
    )
    le = ListEngine(eng)
    eng.reverse_artifacts()
    build_s = time.perf_counter() - t0

    subj = SubjectID("u7")
    rev_s = []
    for _ in range(TRIALS):
        t0 = time.perf_counter()
        page = le.list_objects(subj, "view", "rbac", max_depth=5)
        rev_s.append(time.perf_counter() - t0)
    assert page.source == "reverse", page.source

    # candidate universe the oracle would settle one check at a time
    cands = set()
    token = ""
    while True:
        batch, token = store.get_relation_tuples(
            RelationQuery(namespace="rbac", relation="view"),
            PaginationOptions(token=token),
        )
        cands.update(t.object for t in batch)
        if not token:
            break
    cands = sorted(cands)
    fb = eng.fallback_engine()
    sample = cands[: min(LIST_ORACLE_SAMPLE, len(cands))]
    t0 = time.perf_counter()
    for o in sample:
        fb.subject_is_allowed(RelationTuple("rbac", o, "view", subj), 5)
    per_cand = (time.perf_counter() - t0) / max(1, len(sample))
    oracle_est = per_cand * len(cands)

    rev_med = float(np.median(rev_s))
    return {
        "tuples": len(tuples),
        "candidates": len(cands),
        "oracle_sample": len(sample),
        "matched": len(page.items),
        "build_s": round(build_s, 4),
        "reverse_median_s": round(rev_med, 6),
        "oracle_estimated_s": round(oracle_est, 4),
        "speedup": round(
            oracle_est / rev_med if rev_med > 0 else float("inf"), 1
        ),
    }


def main() -> int:
    gate = "--gate" in sys.argv
    rng = np.random.default_rng(11)
    m_pad = _m_pad(M)
    src = rng.integers(0, M, EDGES, dtype=np.int32)
    dst = rng.integers(0, M, EDGES, dtype=np.int32)

    full_s = []
    for _ in range(TRIALS):
        t0 = time.perf_counter()
        d = build_closure_bitset(src, dst, M, m_pad, K_MAX)
        full_s.append(time.perf_counter() - t0)

    incr_s = []
    dirty_counts = []
    for i in range(TRIALS):
        # one fresh interior edge per trial — the canonical "a write
        # landed, re-close" case the old builder answered with a full
        # O(m^3) rebuild past its 8-edge patch window
        e_src = np.concatenate([src, [np.int32((17 * i + 3) % M)]])
        e_dst = np.concatenate([dst, [np.int32((41 * i + 7) % M)]])
        t0 = time.perf_counter()
        d_new, n_dirty = update_closure_bitset(
            d, src, dst, e_src, e_dst, M, m_pad, K_MAX
        )
        incr_s.append(time.perf_counter() - t0)
        dirty_counts.append(n_dirty)

    full_med = float(np.median(full_s))
    incr_med = float(np.median(incr_s))
    speedup = full_med / incr_med if incr_med > 0 else float("inf")
    reverse = _reverse_rung(d, src, dst, rng)
    listing = _list_rung(rng)
    out = {
        "m": M,
        "edges": EDGES,
        "k_max": K_MAX,
        "trials": TRIALS,
        "full_build_median_s": round(full_med, 4),
        "incremental_median_s": round(incr_med, 4),
        "dirty_rows_median": int(np.median(dirty_counts)),
        "speedup": round(speedup, 2),
        "required_speedup": MIN_SPEEDUP if gate else None,
        "reverse": reverse,
        "reverse_required_speedup": MIN_REVERSE_SPEEDUP if gate else None,
        "list": listing,
        "list_required_speedup": MIN_LIST_SPEEDUP if gate else None,
    }
    print(json.dumps(out), flush=True)
    failed = False
    if gate and speedup < MIN_SPEEDUP:
        print(
            f"closure incremental regression: {speedup:.2f}x < "
            f"{MIN_SPEEDUP}x required",
            file=sys.stderr,
        )
        failed = True
    if gate and reverse["speedup"] < MIN_REVERSE_SPEEDUP:
        print(
            f"reverse incremental regression: {reverse['speedup']:.2f}x < "
            f"{MIN_REVERSE_SPEEDUP}x required",
            file=sys.stderr,
        )
        failed = True
    if gate and listing["speedup"] < MIN_LIST_SPEEDUP:
        print(
            f"list reverse-index regression: {listing['speedup']:.2f}x < "
            f"{MIN_LIST_SPEEDUP}x required",
            file=sys.stderr,
        )
        failed = True
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
