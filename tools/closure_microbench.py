"""Closure-build microbench: full semiring rebuild vs incremental update.

The point of the incremental closure path (keto_tpu.engine.semiring) is that
a small interior edge delta costs proportional to its blast radius, not the
graph. This tool measures exactly that claim at a serving-realistic scale
(m ~ 2048 interior nodes, 3m edges, k_max 4) and — with ``--gate`` — fails
the build when the incremental update after ONE inserted edge is not at
least 5x faster than a full rebuild (median of several trials each).

Pure-host numpy path (no jax import): the gate must answer in seconds and
not depend on which accelerator CI got.

Usage:
    python tools/closure_microbench.py            # print JSON numbers
    python tools/closure_microbench.py --gate     # exit 1 on regression
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from keto_tpu.engine.semiring import (  # noqa: E402
    build_closure_bitset,
    update_closure_bitset,
)

M = int(os.environ.get("CLOSURE_BENCH_M", 2048))
EDGES = int(os.environ.get("CLOSURE_BENCH_EDGES", 3 * M))
K_MAX = int(os.environ.get("CLOSURE_BENCH_KMAX", 4))
TRIALS = int(os.environ.get("CLOSURE_BENCH_TRIALS", 5))
MIN_SPEEDUP = float(os.environ.get("CLOSURE_BENCH_MIN_SPEEDUP", 5.0))


def _m_pad(m: int) -> int:
    return ((m + 255) // 256) * 256


def main() -> int:
    gate = "--gate" in sys.argv
    rng = np.random.default_rng(11)
    m_pad = _m_pad(M)
    src = rng.integers(0, M, EDGES, dtype=np.int32)
    dst = rng.integers(0, M, EDGES, dtype=np.int32)

    full_s = []
    for _ in range(TRIALS):
        t0 = time.perf_counter()
        d = build_closure_bitset(src, dst, M, m_pad, K_MAX)
        full_s.append(time.perf_counter() - t0)

    incr_s = []
    dirty_counts = []
    for i in range(TRIALS):
        # one fresh interior edge per trial — the canonical "a write
        # landed, re-close" case the old builder answered with a full
        # O(m^3) rebuild past its 8-edge patch window
        e_src = np.concatenate([src, [np.int32((17 * i + 3) % M)]])
        e_dst = np.concatenate([dst, [np.int32((41 * i + 7) % M)]])
        t0 = time.perf_counter()
        d_new, n_dirty = update_closure_bitset(
            d, src, dst, e_src, e_dst, M, m_pad, K_MAX
        )
        incr_s.append(time.perf_counter() - t0)
        dirty_counts.append(n_dirty)

    full_med = float(np.median(full_s))
    incr_med = float(np.median(incr_s))
    speedup = full_med / incr_med if incr_med > 0 else float("inf")
    out = {
        "m": M,
        "edges": EDGES,
        "k_max": K_MAX,
        "trials": TRIALS,
        "full_build_median_s": round(full_med, 4),
        "incremental_median_s": round(incr_med, 4),
        "dirty_rows_median": int(np.median(dirty_counts)),
        "speedup": round(speedup, 2),
        "required_speedup": MIN_SPEEDUP if gate else None,
    }
    print(json.dumps(out), flush=True)
    if gate and speedup < MIN_SPEEDUP:
        print(
            f"closure incremental regression: {speedup:.2f}x < "
            f"{MIN_SPEEDUP}x required",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
