#!/usr/bin/env python
"""Autotune gate (tools/check.sh): the feedback controller against a
scripted ledger with a KNOWN response surface.

The in-process AutoTuner drives a synthetic serving system whose
throughput is a deterministic function of its knob vector:

- ``pipeline_depth`` has an interior optimum (too shallow starves the
  device, too deep thrashes HBM) — the controller must climb to it,
  overshoot once, revert, and then HOLD it (convergence + the revert
  path exercised on one seeded run);
- ``encode_workers`` helps monotonically up to its bound — the
  controller must ride it to the bound and stop (bound discipline);
- every applied value is recorded and checked against the declared
  [lo, hi] — a single out-of-bounds write fails the gate;
- a guard flip mid-run must freeze moves instantly and thaw cleanly.

Exit 0 = all invariants hold; exit 1 with a reason otherwise. No server
boot, no device, sub-second runtime: this is the cheap always-on CI
proof that the controller logic converges and respects its rails.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from keto_tpu.engine.autotune import AutoTuner, Knob  # noqa: E402
from keto_tpu.telemetry import MetricsRegistry  # noqa: E402


class World:
    """The scripted system under control: cumulative attribution
    snapshots derived from the current knob vector each window."""

    def __init__(self):
        self.depth = 2
        self.workers = 2
        self.requests = 0
        self.wall = 0.0
        self.stage_s = {"launch": 0.0, "queue": 0.0, "kernel": 0.0}
        self.applied: list[tuple[str, float]] = []

    def throughput(self) -> float:
        # interior optimum at depth=5 (steep enough that one overshoot
        # step regresses past the 5% revert threshold), monotone gain in
        # workers up to the bound
        return (
            1000.0
            - 80.0 * (self.depth - 5) ** 2
            + 30.0 * self.workers
        )

    def advance_window(self) -> None:
        self.requests += int(self.throughput())
        self.wall += 1.0
        # launch dominates until depth settles, then queue's worker knob
        # becomes the bottleneck (two-phase convergence)
        if self.depth != 5:
            self.stage_s["launch"] += 0.6
            self.stage_s["queue"] += 0.2
        else:
            self.stage_s["queue"] += 0.6
            self.stage_s["launch"] += 0.1
        self.stage_s["kernel"] += 0.1

    def snapshot(self) -> dict:
        return {
            "requests": self.requests,
            "entries": self.requests,
            "wall_s": round(self.wall, 6),
            "attributed_s": round(sum(self.stage_s.values()), 6),
            "unattributed_s": 0.0,
            "coverage": 1.0,
            "stages": {
                s: {"seconds": round(v, 6), "share_of_wall": 0.0}
                for s, v in self.stage_s.items()
            },
        }


def fail(msg: str) -> None:
    print(f"autotune gate: FAIL: {msg}")
    sys.exit(1)


def main() -> int:
    world = World()

    def set_depth(v):
        world.applied.append(("pipeline_depth", v))
        world.depth = int(v)

    def set_workers(v):
        world.applied.append(("encode_workers", v))
        world.workers = int(v)

    depth_knob = Knob(
        "pipeline_depth", stage="launch", lo=1, hi=8, step=1,
        read=lambda: world.depth, apply=set_depth,
    )
    worker_knob = Knob(
        "encode_workers", stage="queue", lo=1, hi=6, step=1,
        read=lambda: world.workers, apply=set_workers,
    )
    guard = {"reason": None}
    metrics = MetricsRegistry()
    tuner = AutoTuner(
        [depth_knob, worker_knob],
        attribution=world,
        metrics=metrics,
        min_requests=10,
        revert_threshold=0.05,
        backoff_ticks=2,
        guards=(lambda: guard["reason"],),
    )

    ticks = 60
    for _ in range(ticks):
        world.advance_window()
        tuner.step()

    # -- convergence ---------------------------------------------------------
    if world.depth != 5:
        fail(
            f"pipeline_depth did not converge to the optimum 5 within "
            f"{ticks} ticks (final={world.depth}, "
            f"moves={tuner.moves_total}, reverts={tuner.reverts_total})"
        )
    if world.workers != 6:
        fail(
            f"encode_workers did not reach its bound 6 within {ticks} "
            f"ticks (final={world.workers})"
        )

    # -- revert exercised ----------------------------------------------------
    if tuner.reverts_total < 1:
        fail(
            "the overshoot past depth=5 was never reverted "
            f"(reverts_total={tuner.reverts_total}) — the regression "
            "detector is dead"
        )
    actions = [e["action"] for e in tuner.history()]
    if "revert" not in actions:
        fail("no revert event in the controller history")

    # -- bounds never violated ----------------------------------------------
    bounds = {"pipeline_depth": (1, 8), "encode_workers": (1, 6)}
    for name, value in world.applied:
        lo, hi = bounds[name]
        if not (lo <= value <= hi):
            fail(f"knob {name} applied out-of-bounds value {value}")

    # -- freeze/thaw ---------------------------------------------------------
    guard["reason"] = "breaker_open"
    world.workers = 3  # re-open headroom so a move WOULD happen
    moves_before = tuner.moves_total
    world.advance_window()
    ev = tuner.step()
    if ev["action"] != "frozen" or tuner.moves_total != moves_before:
        fail(f"guard did not freeze moves (event={ev})")
    guard["reason"] = None
    world.advance_window()
    ev = tuner.step()
    if ev["action"] != "move":
        fail(f"controller did not thaw after the guard cleared ({ev})")

    # -- metrics families present -------------------------------------------
    text = metrics.expose()
    for family in (
        "keto_autotune_moves_total",
        "keto_autotune_reverts_total",
        "keto_autotune_knob_value",
        "keto_autotune_frozen",
    ):
        if family not in text:
            fail(f"metric family {family} missing from exposition")

    print(
        f"autotune gate: OK — converged depth=5 workers=6 in <= {ticks} "
        f"ticks, moves={tuner.moves_total}, "
        f"reverts={tuner.reverts_total}, {len(world.applied)} applies "
        "all in bounds, freeze/thaw clean"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
