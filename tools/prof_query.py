"""Profile the closure-engine query hot path at scale WITHOUT a device.

The O(M^3) closure build needs the MXU; the query path only needs a D
matrix with the right shape and a realistic hit rate — its values steer
branch outcomes, not the access pattern. This harness generates a bench
graph, builds the real interior decomposition, fills D synthetically, and
times the object path (batch_check: encode + query) and the array path
(check_ids) with per-stage breakdowns.

Usage: python tools/prof_query.py [n_tuples] [batch] [iters]
"""

import os
import sys
import time

os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench
from keto_tpu.engine.closure import ClosureCheckEngine, _ClosureArtifacts
from keto_tpu.graph import SnapshotManager
from keto_tpu.graph.interior import build_interior
from keto_tpu.relationtuple import RelationTuple, SubjectID, SubjectSet


def synthetic_closure(ig, m_pad: int, rng) -> np.ndarray:
    """uint8[m_pad, m_pad] with plausible bounded distances: mostly INF,
    small distances on a minority, matching the bench's ~12% allow rate."""
    d = np.full((m_pad, m_pad), 255, dtype=np.uint8)
    m = ig.m
    # ~8% of interior pairs reachable, distances 1..4
    n_fill = int(m * m * 0.08)
    rows = rng.integers(m, size=n_fill)
    cols = rng.integers(m, size=n_fill)
    vals = rng.integers(1, 5, size=n_fill).astype(np.uint8)
    d[rows, cols] = vals
    idx = np.arange(m)
    d[idx, idx] = 0
    return d


def main():
    n_tuples = int(sys.argv[1]) if len(sys.argv) > 1 else 10_000_000
    batch = int(sys.argv[2]) if len(sys.argv) > 2 else 4096
    iters = int(sys.argv[3]) if len(sys.argv) > 3 else 30

    rng = np.random.default_rng(7)
    t0 = time.time()
    store, sample, _roots = bench.gen_rbac(n_tuples, rng)
    print(f"gen: {time.time()-t0:.1f}s  tuples={len(store)}", flush=True)

    t0 = time.time()
    snapshots = SnapshotManager(store)
    snap = snapshots.snapshot()
    print(f"snapshot: {time.time()-t0:.1f}s nodes={snap.num_nodes}", flush=True)

    t0 = time.time()
    ig = build_interior(snap)
    print(f"interior: {time.time()-t0:.1f}s m={ig.m}", flush=True)

    engine = ClosureCheckEngine(
        snapshots, max_depth=5, interior_limit=40960, query_mode="host"
    )
    # hand-build the artifacts with a synthetic D (no device build)
    art = _ClosureArtifacts.__new__(_ClosureArtifacts)
    art.snap = snap
    art.ig = ig
    art.k_max = 4
    from keto_tpu.engine.closure import _bucket_mult

    art.m_pad = _bucket_mult(ig.m + 1, 256)
    art.pad = art.m_pad - 1
    art.d = None
    t0 = time.time()
    art.d_host = synthetic_closure(ig, art.m_pad, rng)
    print(f"synthetic D: {time.time()-t0:.1f}s  {art.m_pad}^2 "
          f"= {art.m_pad*art.m_pad/1e6:.0f} MB", flush=True)
    engine._state = art

    def to_requests(skeys, dkeys):
        return [
            RelationTuple(
                namespace=s[0], object=s[1], relation=s[2],
                subject=SubjectID(d[0]) if len(d) == 1
                else SubjectSet(namespace=d[0], object=d[1], relation=d[2]),
            )
            for s, d in zip(skeys, dkeys)
        ]

    import gc

    # ---- array path (check_ids)
    lookup = snap.vocab.lookup
    dummy = snap.dummy_node
    enc_batches = []
    for _ in range(iters):
        skeys, dkeys = sample(rng, batch)
        s_ids = np.array(
            [v if (v := lookup(k)) is not None else dummy for k in skeys],
            np.int64)
        d_ids = np.array(
            [v if (v := lookup(k)) is not None else dummy for k in dkeys],
            np.int64)
        is_id = np.fromiter((len(k) == 1 for k in dkeys), bool, count=batch)
        enc_batches.append((s_ids, d_ids, is_id))
    res = engine.check_ids(*enc_batches[0])
    print(f"allowed_frac={res.mean():.3f}", flush=True)
    gc.collect(); gc.disable()
    best = 0.0
    for _pass in range(2):
        lats = []
        t_all = time.time()
        for s_ids, d_ids, is_id in enc_batches:
            t0 = time.perf_counter()
            engine.check_ids(s_ids, d_ids, is_id)
            lats.append(time.perf_counter() - t0)
        rps = batch * iters / (time.time() - t_all)
        if rps > best:
            best, keep = rps, lats
    gc.enable()
    print(f"check_ids: {best:,.0f} rps  p50={np.percentile(keep,50)*1e3:.2f}ms "
          f"p95={np.percentile(keep,95)*1e3:.2f}ms", flush=True)

    # ---- object path (batch_check)
    batches = [to_requests(*sample(rng, batch)) for _ in range(iters)]
    engine.batch_check(batches[0])
    gc.collect(); gc.disable()
    best_o = 0.0
    for _pass in range(2):
        lats = []
        t_all = time.time()
        for reqs in batches:
            t0 = time.perf_counter()
            engine.batch_check(reqs)
            lats.append(time.perf_counter() - t0)
        rps = batch * iters / (time.time() - t_all)
        if rps > best_o:
            best_o, keep_o = rps, lats
    gc.enable()
    print(f"batch_check: {best_o:,.0f} rps  "
          f"p50={np.percentile(keep_o,50)*1e3:.2f}ms "
          f"p95={np.percentile(keep_o,95)*1e3:.2f}ms", flush=True)

    # ---- stage breakdown of the object path (one batch, repeated)
    reqs = batches[0]
    n = len(reqs)

    def t_stage(fn, reps=10):
        fn()
        t0 = time.perf_counter()
        for _ in range(reps):
            fn()
        return (time.perf_counter() - t0) / reps * 1e3

    skeys = [(r.namespace, r.object, r.relation) for r in reqs]
    tkeys = [
        (s.id,) if type(s) is SubjectID
        else (s.namespace, s.object, s.relation)
        for s in (r.subject for r in reqs)
    ]
    ms_keys = t_stage(lambda: (
        [(r.namespace, r.object, r.relation) for r in reqs],
        [(s.id,) if type(s) is SubjectID
         else (s.namespace, s.object, s.relation)
         for s in (r.subject for r in reqs)],
    ))
    ms_lookup = t_stage(lambda: (
        snap.vocab.lookup_bulk(skeys), snap.vocab.lookup_bulk(tkeys)))
    s_ids, d_ids, is_id = enc_batches[0]
    ms_arrays = t_stage(lambda: engine._check_arrays(
        snap, art, s_ids.copy(), d_ids.copy(), is_id,
        np.full(n, 5, np.int32)))
    print(f"stages (ms/batch of {n}): keys={ms_keys:.2f} "
          f"lookup_bulk={ms_lookup:.2f} check_arrays={ms_arrays:.2f}",
          flush=True)

    # ---- expand p50/p95 over the bench's root sample (graph frozen out
    # of the cyclic GC, as the serving registry does at boot)
    import gc as _gc

    _gc.freeze()
    from keto_tpu.engine.device import SnapshotExpandEngine

    expander = SnapshotExpandEngine(snapshots, max_depth=5)
    exp_lat = []
    n_nodes = []

    def count(tree):
        return 1 + sum(count(c) for c in tree.children)

    for key in _roots:
        subject = SubjectSet(namespace=key[0], object=key[1], relation=key[2])
        t0 = time.perf_counter()
        tree = expander.build_tree(subject, max_depth=3)
        exp_lat.append(time.perf_counter() - t0)
        n_nodes.append(0 if tree is None else count(tree))
    print(f"expand: p50={np.percentile(exp_lat,50)*1e3:.2f}ms "
          f"p95={np.percentile(exp_lat,95)*1e3:.2f}ms "
          f"max={max(exp_lat)*1e3:.1f}ms "
          f"nodes_p50={int(np.percentile(n_nodes,50))} "
          f"nodes_p95={int(np.percentile(n_nodes,95))} "
          f"nodes_max={max(n_nodes)}", flush=True)

    from keto_tpu import native
    print(f"native={native.available()}", flush=True)


if __name__ == "__main__":
    main()
