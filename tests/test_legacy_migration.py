"""Legacy single-table data migration (reference single_table.go:26-98 +
cmd/namespace/migrate_legacy.go:18-117): per-namespace v0.6 tables migrate
into the current store, invalid subjects are skipped and surfaced, down
drops the legacy table."""

import pytest
from click.testing import CliRunner

from keto_tpu.cli import cli
from keto_tpu.namespace.definitions import MemoryNamespaceManager, Namespace
from keto_tpu.persistence import SQLiteTupleStore
from keto_tpu.persistence.legacy import (
    ErrInvalidTuples,
    SingleTableMigrator,
    legacy_table_name,
)
from keto_tpu.relationtuple import RelationQuery


def _fixture_store(path, namespaces=(Namespace(name="videos", id=7),)):
    store = SQLiteTupleStore(
        str(path), namespace_manager=MemoryNamespaceManager(*namespaces)
    )
    return store


def _seed_legacy(store, ns, rows):
    m = SingleTableMigrator(store)
    m.create_legacy_table(ns)
    store._conn.executemany(
        f'INSERT INTO "{legacy_table_name(ns)}" '
        "(shard_id, object, relation, subject, commit_time) "
        "VALUES (?, ?, ?, ?, CURRENT_TIMESTAMP)",
        [("s", o, r, s) for o, r, s in rows],
    )
    store._conn.commit()
    return m


class TestSingleTableMigrator:
    def test_discovers_legacy_namespaces(self, tmp_path):
        store = _fixture_store(tmp_path / "db.sqlite")
        ns = store.namespace_manager.get_namespace_by_name("videos")
        m = _seed_legacy(store, ns, [("o", "r", "alice")])
        assert [n.name for n in m.legacy_namespaces()] == ["videos"]

    def test_migrates_rows_with_subject_reparse(self, tmp_path):
        store = _fixture_store(tmp_path / "db.sqlite")
        ns = store.namespace_manager.get_namespace_by_name("videos")
        m = _seed_legacy(
            store,
            ns,
            [
                ("/cats", "owner", "cat lady"),
                # subject-set string grammar ns:obj#rel (definitions.go:137-142)
                ("/cats/1.mp4", "view", "videos:/cats#owner"),
            ],
        )
        migrated, invalid = m.migrate_namespace(ns)
        assert migrated == 2 and invalid == []
        tuples, _ = store.get_relation_tuples(RelationQuery(namespace="videos"))
        assert len(tuples) == 2
        by_obj = {t.object: t for t in tuples}
        assert by_obj["/cats"].subject.id == "cat lady"
        sub = by_obj["/cats/1.mp4"].subject
        assert (sub.namespace, sub.object, sub.relation) == (
            "videos", "/cats", "owner",
        )

    def test_invalid_subjects_skipped_and_surfaced(self, tmp_path):
        store = _fixture_store(tmp_path / "db.sqlite")
        ns = store.namespace_manager.get_namespace_by_name("videos")
        # "x#y" has a '#' (so it must be a subject set) but no ':' — the
        # grammar rejects it (reference SubjectFromString)
        m = _seed_legacy(
            store, ns, [("o1", "r", "good"), ("o2", "r", "x#y")]
        )
        with pytest.raises(ErrInvalidTuples) as e:
            m.migrate_namespace(ns)
        assert len(e.value.invalid) == 1
        assert e.value.invalid[0].object == "o2"
        # the good row still migrated (skip-and-continue, like the reference)
        tuples, _ = store.get_relation_tuples(RelationQuery(namespace="videos"))
        assert len(tuples) == 1

    def test_down_drops_legacy_table(self, tmp_path):
        store = _fixture_store(tmp_path / "db.sqlite")
        ns = store.namespace_manager.get_namespace_by_name("videos")
        m = _seed_legacy(store, ns, [("o", "r", "alice")])
        m.migrate_namespace(ns)
        m.migrate_down(ns)
        assert m.legacy_namespaces() == []

    def test_unconfigured_namespace_table_refuses_migration(self, tmp_path):
        store = _fixture_store(tmp_path / "db.sqlite", namespaces=())
        m = SingleTableMigrator(store)
        m.create_legacy_table(Namespace(name="x", id=42))
        found = m.legacy_namespaces()
        assert found[0].name.startswith("<unconfigured:")
        with pytest.raises(Exception, match="namespace config"):
            m.migrate_namespace(found[0])


class TestNamespaceMigrateCli:
    def _cfg(self, tmp_path):
        cfg = tmp_path / "keto.yml"
        cfg.write_text(
            f"dsn: sqlite://{tmp_path}/keto.db\n"
            "namespaces:\n  - name: videos\n    id: 7\n"
        )
        return str(cfg)

    def test_legacy_end_to_end(self, tmp_path):
        cfg = self._cfg(tmp_path)
        store = _fixture_store(tmp_path / "keto.db")
        ns = Namespace(name="videos", id=7)
        _seed_legacy(
            store, ns, [("/cats", "owner", "cat lady")]
        )
        store._conn.close()

        r = CliRunner()
        res = r.invoke(
            cli, ["namespace", "migrate", "status", "-c", cfg]
        )
        assert res.exit_code == 0 and "videos" in res.output

        res = r.invoke(
            cli, ["namespace", "migrate", "legacy", "-c", cfg, "--yes"]
        )
        assert res.exit_code == 0, res.output
        assert "migrated 1 tuples" in res.output
        assert "Successfully migrated down" in res.output

        res = r.invoke(
            cli, ["namespace", "migrate", "status", "-c", cfg]
        )
        assert "no legacy namespace tables found" in res.output

        # the migrated tuple is served by the current store
        check = SQLiteTupleStore(
            str(tmp_path / "keto.db"),
            namespace_manager=MemoryNamespaceManager(ns),
        )
        tuples, _ = check.get_relation_tuples(
            RelationQuery(namespace="videos")
        )
        assert len(tuples) == 1

    def test_deprecated_verbs_are_noops(self, tmp_path):
        r = CliRunner()
        for verb in ("up", "down"):
            res = r.invoke(cli, ["namespace", "migrate", verb, "videos"])
            assert res.exit_code == 0
            assert "deprecated" in res.output
