"""Online autotuner (engine/autotune.py): the ledger-driven feedback
controller over the serving knobs.

Deterministic drills against a scripted attribution ledger: bounded hill
climbing converges to the helpful bound within a handful of control
ticks, a throughput regression reverts the move and backs the knob off,
SLO burn / guard signals freeze all moves (and roll back a pending one),
bounds are never exceeded no matter how adversarial the traffic, and
every decision is visible as a flight record with before/after stage
breakdowns. Plus the hot-knob validation seam (Config.set_hot /
validate_knob — satellite 1), the CheckBatcher.reconfigure quiesce seam,
and one end-to-end pass proving a knob move shows up in all three
surfaces at once: flight kind=autotune, /debug/autotune, and
keto_autotune_moves_total.
"""

import threading
import time

import httpx
import pytest

from keto_tpu.driver.config import (
    Config,
    HOT_ENGINE_KEYS,
    HOT_KNOB_KEYS,
    validate_knob,
)
from keto_tpu.engine.autotune import AutoTuner, Knob
from keto_tpu.utils.errors import ErrMalformedInput
from keto_tpu.telemetry import MetricsRegistry
from keto_tpu.telemetry.flight import FlightRecorder


class _ScriptedLedger:
    """Cumulative attribution snapshots under test control: each
    ``advance`` is one control window's worth of traffic."""

    def __init__(self):
        self._requests = 0
        self._wall = 0.0
        self._stages: dict = {}

    def advance(self, requests: int, wall_s: float, stages: dict) -> None:
        self._requests += int(requests)
        self._wall += float(wall_s)
        for s, v in stages.items():
            self._stages[s] = self._stages.get(s, 0.0) + float(v)

    def snapshot(self) -> dict:
        return {
            "requests": self._requests,
            "entries": self._requests,
            "wall_s": round(self._wall, 6),
            "attributed_s": round(sum(self._stages.values()), 6),
            "unattributed_s": 0.0,
            "coverage": 1.0,
            "stages": {
                s: {"seconds": round(v, 6), "share_of_wall": 0.0}
                for s, v in self._stages.items()
            },
        }


class _Holder:
    """A knob target recording every applied value."""

    def __init__(self, value):
        self.value = value
        self.applied: list = []

    def read(self):
        return self.value

    def apply(self, v):
        self.applied.append(v)
        self.value = v


class _FakeSLO:
    def __init__(self):
        self.burn = 0.0
        self.fast_window_s = 300.0
        self.alert_burn_rate = 14.4

    def burn_rate(self, window_s):
        return self.burn


def _knob(holder, name="encode_workers", stage="queue", lo=1, hi=8,
          step=1, **kw):
    return Knob(
        name, stage=stage, lo=lo, hi=hi, step=step,
        read=holder.read, apply=holder.apply, **kw,
    )


def _tuner(knobs, ledger, **kw):
    kw.setdefault("min_requests", 10)
    kw.setdefault("backoff_ticks", 3)
    return AutoTuner(knobs, attribution=ledger, **kw)


class TestHillClimb:
    def test_converges_to_bound_within_n_steps(self):
        ledger = _ScriptedLedger()
        holder = _Holder(2)
        t = _tuner([_knob(holder)], ledger)
        # queue-bound traffic whose throughput rewards every raise: the
        # climber must reach the upper bound and then hold steady
        for _ in range(20):
            ledger.advance(100 + 50 * holder.value, 1.0, {"queue": 0.6})
            t.step()
        assert holder.value == 8
        assert all(1 <= v <= 8 for v in holder.applied)
        assert t.moves_total == 6  # 2 -> 8 in unit steps, then steady
        assert t.reverts_total == 0
        assert t.step()["action"] in ("steady", "idle")

    def test_moves_the_bottleneck_stages_knob_only(self):
        ledger = _ScriptedLedger()
        q, k = _Holder(2), _Holder(0.5)
        t = _tuner(
            [
                _knob(q, name="encode_workers", stage="queue"),
                _knob(
                    k, name="hbm_budget_frac", stage="kernel",
                    lo=0.1, hi=0.95, step=0.05, integer=False,
                ),
            ],
            ledger,
        )
        t.step()  # warmup
        ledger.advance(100, 1.0, {"kernel": 0.7, "queue": 0.1})
        ev = t.step()
        assert ev["action"] == "move" and ev["knob"] == "hbm_budget_frac"
        assert k.applied and not q.applied

    def test_lower_is_better_direction(self):
        ledger = _ScriptedLedger()
        page = _Holder(2048)
        t = _tuner(
            [
                _knob(
                    page, name="expand_page_size", stage="serialize",
                    lo=256, hi=8192, step=256, higher_helps=False,
                )
            ],
            ledger,
        )
        t.step()
        ledger.advance(100, 1.0, {"serialize": 0.8})
        ev = t.step()
        assert ev["action"] == "move"
        assert page.value == 1792 and ev["direction"] == -1

    def test_disabled_knob_and_unowned_stage_never_move(self):
        ledger = _ScriptedLedger()
        holder = _Holder(2)
        t = _tuner([_knob(holder, enabled=False)], ledger)
        t.step()
        ledger.advance(100, 1.0, {"queue": 0.9, "unattributed": 2.0})
        assert t.step()["action"] == "steady"
        assert holder.applied == []


class TestRevert:
    def test_revert_on_regression_with_backoff(self):
        ledger = _ScriptedLedger()
        holder = _Holder(2)
        flight = FlightRecorder(capacity=64)
        t = _tuner(
            [_knob(holder)], ledger, flight=flight, revert_threshold=0.05
        )
        t.step()  # warmup
        ledger.advance(100, 1.0, {"queue": 0.6})
        assert t.step()["action"] == "move"  # 2 -> 3, baseline 100/s
        ledger.advance(50, 1.0, {"queue": 0.6})  # throughput halves
        ev = t.step()
        assert ev["action"] == "revert" and ev["reason"] == "regression"
        assert holder.value == 2
        assert t.reverts_total == 1
        # the reverted (knob, direction) sits out backoff_ticks ticks
        for _ in range(3):
            ledger.advance(100, 1.0, {"queue": 0.6})
            assert t.step()["action"] == "steady"
            assert holder.value == 2
        ledger.advance(100, 1.0, {"queue": 0.6})
        assert t.step()["action"] == "move"  # backoff expired: retries
        # the revert flight record carries BOTH breakdowns
        revert = [
            r for r in flight.records() if r.get("action") == "revert"
        ][0]
        assert revert["kind"] == "autotune"
        assert "queue" in revert["before"] and "queue" in revert["after"]

    def test_commit_on_improvement_keeps_value(self):
        ledger = _ScriptedLedger()
        holder = _Holder(2)
        t = _tuner([_knob(holder)], ledger)
        t.step()
        ledger.advance(100, 1.0, {"queue": 0.6})
        t.step()  # move 2 -> 3
        ledger.advance(150, 1.0, {"queue": 0.6})  # improved
        ev = t.step()  # commit, then the next move in the same tick
        assert holder.value == 4
        assert t.reverts_total == 0
        assert ev["action"] == "move"

    def test_bounds_never_exceeded_under_adversarial_traffic(self):
        ledger = _ScriptedLedger()
        holder = _Holder(4)
        t = _tuner([_knob(holder)], ledger, revert_threshold=0.05)
        # throughput that punishes every second window: moves and reverts
        # interleave, and no applied value may ever leave [lo, hi]
        for i in range(40):
            rate = 200 if i % 2 else 40
            ledger.advance(rate, 1.0, {"queue": 0.6})
            t.step()
        assert all(1 <= v <= 8 for v in holder.applied)
        assert 1 <= holder.value <= 8
        assert t.reverts_total > 0

    def test_apply_failure_disqualifies_the_knob(self):
        ledger = _ScriptedLedger()

        class _Refusing(_Holder):
            def apply(self, v):
                raise RuntimeError("component closed")

        bad, good = _Refusing(2), _Holder(0.5)
        t = _tuner(
            [
                _knob(bad, name="encode_workers", stage="queue"),
                _knob(
                    good, name="hbm_budget_frac", stage="queue",
                    lo=0.1, hi=0.95, step=0.05, integer=False,
                ),
            ],
            ledger,
        )
        t.step()
        ledger.advance(100, 1.0, {"queue": 0.6})
        ev = t.step()
        # the refusing knob is skipped; its stage-mate gets the move
        assert ev["action"] == "move" and ev["knob"] == "hbm_budget_frac"
        assert bad.value == 2 and good.applied


class TestFreeze:
    def test_slo_burn_freezes_moves(self):
        ledger = _ScriptedLedger()
        holder = _Holder(2)
        slo = _FakeSLO()
        t = _tuner([_knob(holder)], ledger, slo=slo)
        t.step()
        slo.burn = 20.0  # past alert_burn_rate (freeze inherits it)
        ledger.advance(100, 1.0, {"queue": 0.6})
        ev = t.step()
        assert ev["action"] == "frozen" and ev["reason"] == "slo_burn"
        assert holder.applied == [] and t.moves_total == 0
        slo.burn = 0.0
        ledger.advance(100, 1.0, {"queue": 0.6})
        assert t.step()["action"] == "move"  # thawed

    def test_freeze_reverts_the_pending_move(self):
        ledger = _ScriptedLedger()
        holder = _Holder(2)
        slo = _FakeSLO()
        t = _tuner([_knob(holder)], ledger, slo=slo)
        t.step()
        ledger.advance(100, 1.0, {"queue": 0.6})
        t.step()  # move 2 -> 3, now pending
        slo.burn = 20.0
        ledger.advance(200, 1.0, {"queue": 0.6})  # even improving traffic
        ev = t.step()
        assert ev["action"] == "revert" and ev["reason"] == "slo_burn"
        assert holder.value == 2

    def test_guard_freezes_with_its_reason(self):
        ledger = _ScriptedLedger()
        holder = _Holder(2)
        open_ = {"v": False}
        t = _tuner(
            [_knob(holder)], ledger,
            guards=(lambda: "breaker_open" if open_["v"] else None,),
        )
        t.step()
        open_["v"] = True
        ledger.advance(100, 1.0, {"queue": 0.6})
        ev = t.step()
        assert ev["action"] == "frozen" and ev["reason"] == "breaker_open"
        assert t.snapshot()["frozen"] == "breaker_open"

    def test_kill_switch_short_circuits_and_resets(self):
        ledger = _ScriptedLedger()
        holder = _Holder(2)
        enabled = {"v": True}
        t = _tuner(
            [_knob(holder)], ledger, enabled_fn=lambda: enabled["v"]
        )
        t.step()
        ledger.advance(100, 1.0, {"queue": 0.6})
        t.step()  # move pending
        enabled["v"] = False
        ledger.advance(10, 1.0, {"queue": 0.6})
        assert t.step()["action"] == "disabled"
        assert t.snapshot()["enabled"] is False
        # re-enabling starts from a fresh window: first tick is warmup,
        # the stale pending move is never judged against stale baselines
        enabled["v"] = True
        assert t.step()["action"] == "warmup"

    def test_idle_window_makes_no_move(self):
        ledger = _ScriptedLedger()
        holder = _Holder(2)
        t = _tuner([_knob(holder)], ledger, min_requests=32)
        t.step()
        ledger.advance(5, 1.0, {"queue": 0.6})
        assert t.step()["action"] == "idle"
        assert holder.applied == []


class TestVisibilityPlumbing:
    def test_metrics_and_history_and_snapshot(self):
        ledger = _ScriptedLedger()
        holder = _Holder(2)
        m = MetricsRegistry()
        flight = FlightRecorder(capacity=64)
        t = _tuner([_knob(holder)], ledger, metrics=m, flight=flight)
        t.step()
        ledger.advance(100, 1.0, {"queue": 0.6})
        t.step()  # move
        ledger.advance(40, 1.0, {"queue": 0.6})
        t.step()  # revert
        text = m.expose()
        assert (
            'keto_autotune_moves_total{direction="up",'
            'knob="encode_workers"} 1' in text
            or 'keto_autotune_moves_total{knob="encode_workers",'
            'direction="up"} 1' in text
        )
        assert "keto_autotune_reverts_total 1" in text
        # the per-knob gauge samples the LIVE value (post-revert)
        assert (
            'keto_autotune_knob_value{knob="encode_workers"} 2' in text
        )
        hist = t.history()
        assert hist[0]["action"] == "revert"  # newest first
        assert hist[1]["action"] == "move"
        snap = t.snapshot()
        assert snap["moves_total"] == 1 and snap["reverts_total"] == 1
        assert snap["knobs"]["encode_workers"]["value"] == 2
        kinds = {r.get("kind") for r in flight.records()}
        assert kinds == {"autotune"}

    def test_daemon_start_stop(self):
        ledger = _ScriptedLedger()
        holder = _Holder(2)
        t = _tuner([_knob(holder)], ledger, interval_s=0.01)
        t.start()
        t.start()  # idempotent
        deadline = time.time() + 5
        while t.ticks < 3 and time.time() < deadline:
            time.sleep(0.01)
        t.stop()
        assert t.ticks >= 3
        assert t.snapshot()["running"] is False


class TestKnobRecord:
    def test_clamp_and_validation(self):
        h = _Holder(2)
        k = _knob(h, lo=1, hi=8, step=1)
        assert k.clamp(0) == 1 and k.clamp(99) == 8 and k.clamp(3.6) == 4
        with pytest.raises(ValueError):
            _knob(h, lo=8, hi=1)
        with pytest.raises(ValueError):
            _knob(h, step=0)

    def test_per_knob_config_overrides_via_registry_builder(self):
        from keto_tpu.driver import Registry

        cfg = Config(
            values={
                "namespaces": [{"id": 1, "name": "n"}],
                "log": {"level": "error"},
                "autotune": {
                    "enabled": True,
                    "knobs": {
                        "pipeline_depth": {"enabled": False},
                        "encode_workers": {"max": 4, "step": 2},
                    },
                },
            },
            env={},
        )
        reg = Registry(cfg)
        try:
            t = reg.autotuner()
            knobs = {k.name: k for k in t.knobs}
            assert knobs["pipeline_depth"].enabled is False
            assert knobs["encode_workers"].hi == 4
            assert knobs["encode_workers"].step == 2
            # the reply-stage virtual knob is always present
            assert "hedge_delay_ms" in knobs
        finally:
            reg._batcher.close()


class TestHotKnobValidation:
    """Satellite 1: every hot-reload/graft value passes its schema bounds
    before a live component can see it."""

    def test_set_hot_validates_bounds(self):
        cfg = Config(values={"dsn": "memory"}, env={})
        cfg.set_hot("engine.pipeline_depth", 4)
        assert cfg.get("engine.pipeline_depth") == 4
        with pytest.raises(ErrMalformedInput):
            cfg.set_hot("engine.pipeline_depth", -1)
        with pytest.raises(ErrMalformedInput):
            cfg.set_hot("engine.encode_workers", 0)
        with pytest.raises(ErrMalformedInput):
            cfg.set_hot("engine.memory.hbm_budget_frac", 1.5)
        with pytest.raises(ErrMalformedInput):
            cfg.set_hot("serve.read.max_freshness_wait_s", -2)
        cfg.clear_hot("engine.pipeline_depth")
        assert cfg.get("engine.pipeline_depth") == 2  # back to default

    def test_set_hot_rejects_unregistered_keys(self):
        cfg = Config(values={"dsn": "memory"}, env={})
        with pytest.raises(ErrMalformedInput, match="not a registered"):
            cfg.set_hot("engine.batch_window_us", 100)
        with pytest.raises(ErrMalformedInput):
            cfg.set_hot("dsn", "sqlite://elsewhere")

    def test_every_registered_knob_has_a_schema_entry(self):
        for key in HOT_KNOB_KEYS:
            validate_knob(key, 1 if key in HOT_ENGINE_KEYS else 1.0)

    def test_reload_graft_rejects_out_of_bounds_hot_value(self, tmp_path):
        import json

        path = tmp_path / "keto.json"
        doc = {
            "dsn": "memory",
            "namespaces": [{"id": 1, "name": "n"}],
            "serve": {"read": {"max_freshness_wait_s": 5.0}},
        }
        path.write_text(json.dumps(doc))
        cfg = Config(config_file=str(path), env={})
        assert cfg.get("serve.read.max_freshness_wait_s") == 5.0
        # jsonschema bounds on the subtree catch what the whole-file
        # validation can't: serve is immutable, so the fresh file's serve
        # block validates, but the graft is per-key and must re-check
        doc["serve"]["read"]["max_freshness_wait_s"] = 9.0
        path.write_text(json.dumps(doc))
        applied = cfg.reload()
        assert "serve.read.max_freshness_wait_s" in applied
        assert cfg.get("serve.read.max_freshness_wait_s") == 9.0


class _SplitEngine:
    """Split-phase engine for reconfigure drills (mirrors the
    test_faults.py stand-in)."""

    def pipeline_supported(self):
        return True

    def encode_batch(self, requests, max_depth=0, depths=None):
        return _Enc(requests)

    def launch_encoded(self, enc):
        return enc

    def decode_launched(self, launched):
        return [True] * len(launched.requests)

    def batch_check(self, requests, max_depth=0, depths=None):
        return [True] * len(requests)


class _Enc:
    version = 0

    def __init__(self, requests):
        self.requests = list(requests)

    def keys(self):
        return [(r.object, 0, 0) for r in self.requests]

    def compact(self, keep):
        self.requests = [self.requests[i] for i in keep]

    def release(self):
        pass


def _tup(i: int = 0):
    from keto_tpu.relationtuple.definitions import (
        RelationTuple,
        SubjectID,
    )

    return RelationTuple(
        namespace="n", object=f"o{i}", relation="view",
        subject=SubjectID(id="alice"),
    )


class TestBatcherReconfigure:
    """The quiesce seam the pipeline_depth/encode_workers knobs ride."""

    def test_resize_pipeline_serves_before_and_after(self):
        from keto_tpu.engine.batcher import CheckBatcher

        b = CheckBatcher(
            _SplitEngine(), window_s=0, pipeline_depth=2, encode_workers=1
        )
        try:
            assert b.pipelined is True
            assert b.check(_tup()) is True
            assert b.reconfigure(pipeline_depth=4, encode_workers=3)
            assert b.pipeline_depth == 4 and b.encode_workers == 3
            assert b.check(_tup(1)) is True
            stats = b.pipeline_stats()
            assert stats["pipeline_depth"] == 4
            assert stats["encode_workers"] == 3
        finally:
            b.close()

    def test_noop_reconfigure_returns_false(self):
        from keto_tpu.engine.batcher import CheckBatcher

        b = CheckBatcher(
            _SplitEngine(), window_s=0, pipeline_depth=2, encode_workers=2
        )
        try:
            assert b.reconfigure(pipeline_depth=2, encode_workers=2) is False
            assert b.reconfigure() is False
        finally:
            b.close()

    def test_serial_to_pipelined_transition(self):
        from keto_tpu.engine.batcher import CheckBatcher

        b = CheckBatcher(_SplitEngine(), window_s=0, pipeline_depth=0)
        try:
            assert b.pipelined is False
            assert b.check(_tup()) is True
            assert b.reconfigure(pipeline_depth=2, encode_workers=2)
            assert b.pipelined is True
            assert b.check(_tup(1)) is True
        finally:
            b.close()

    def test_reconfigure_after_close_raises(self):
        from keto_tpu.engine.batcher import BatcherClosed, CheckBatcher

        b = CheckBatcher(_SplitEngine(), window_s=0, pipeline_depth=1)
        b.close()
        with pytest.raises(BatcherClosed):
            b.reconfigure(pipeline_depth=2)


@pytest.fixture(scope="module")
def autotune_server():
    from tests.test_api_server import ServerFixture

    cfg = Config(
        values={
            "namespaces": [{"id": 1, "name": "n"}],
            "log": {"level": "error"},
            "serve": {
                "read": {"port": 0, "host": "127.0.0.1"},
                "write": {"port": 0, "host": "127.0.0.1"},
            },
            # interval far beyond the test runtime: the daemon thread
            # exists but the test drives step() deterministically
            "autotune": {
                "enabled": True,
                "interval_s": 600.0,
                "min_requests": 10,
            },
        },
        env={},
    )
    s = ServerFixture(cfg)
    yield s
    s.stop()


class TestEndToEndVisibility:
    """ISSUE acceptance: one knob move visible in the flight recorder
    (kind=autotune), /debug/autotune, and keto_autotune_moves_total —
    end to end through a live server."""

    def test_move_visible_in_flight_debug_and_metrics(
        self, autotune_server
    ):
        reg = autotune_server.registry
        tuner = reg._autotuner
        assert tuner is not None and tuner.snapshot()["running"]
        # swap in a scripted ledger: the move decision is deterministic,
        # but it lands on the REAL batcher/config/metrics/flight
        ledger = _ScriptedLedger()
        tuner._attribution = ledger
        tuner._last = None
        before_workers = reg._batcher.encode_workers
        tuner.step()  # warmup
        ledger.advance(100, 1.0, {"queue": 0.6})
        ev = tuner.step()
        assert ev["action"] == "move" and ev["knob"] == "encode_workers"
        # the REAL component resized, and config agrees with it
        assert reg._batcher.encode_workers == before_workers + 1
        assert (
            reg.config.get("engine.encode_workers")
            == before_workers + 1
        )
        base = f"http://127.0.0.1:{autotune_server.read_port}"
        # surface 1: the flight recorder
        recs = httpx.get(
            f"{base}/debug/flight", params={"n": 200}, timeout=30
        ).json()["records"]
        auto = [r for r in recs if r.get("kind") == "autotune"]
        assert auto and auto[0]["knob"] == "encode_workers"
        assert "queue" in auto[0]["before"]
        # surface 2: /debug/autotune
        doc = httpx.get(f"{base}/debug/autotune", timeout=30).json()
        assert doc["enabled"] is True
        assert doc["moves_total"] >= 1
        assert doc["knobs"]["encode_workers"]["value"] == (
            before_workers + 1
        )
        assert doc["history"][0]["action"] == "move"
        # surface 3: the metrics plane
        text = httpx.get(f"{base}/metrics", timeout=30).text
        assert "keto_autotune_moves_total" in text
        assert 'knob="encode_workers"' in text
        assert "keto_autotune_knob_value" in text
