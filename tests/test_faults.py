"""The fault matrix: every self-healing path driven deterministically via
keto_tpu.faults (ISSUE 1 acceptance). Covers, in rough blast-radius order:

- fault registry semantics (arm/fire counts, env knob, fork snapshots)
- dispatcher death -> watchdog restart, in-flight futures failed typed
- queue full -> load shed with 429/RESOURCE_EXHAUSTED semantics
- close() -> queued/in-flight futures fail BatcherClosed, never hang
- device failure (raise AND NaN garbage) -> circuit breaker -> host
  fallback -> health NOT_SERVING -> recovery probe -> SERVING again
- client retry: backoff+jitter schedule, deadline honored end-to-end
- replica SIGKILL -> supervisor respawn via zygote + delta-log resync
- delta-stream drop -> version gap -> resync handshake refills it
- replica.crash fault inherited at fork -> whole-pool crash -> heal
"""

import asyncio
import os
import signal
import threading
import time

import httpx
import pytest

from keto_tpu.engine.batcher import (
    BatcherClosed,
    BatcherOverloaded,
    CheckBatcher,
    DispatcherCrashed,
)
from keto_tpu.engine.fallback import DeviceFallbackEngine
from keto_tpu.faults import FAULTS, FaultInjected, FaultRegistry
from keto_tpu.relationtuple.definitions import RelationTuple, SubjectID
from keto_tpu.telemetry import MetricsRegistry


@pytest.fixture(autouse=True)
def _clean_faults():
    FAULTS.reset()
    yield
    FAULTS.reset()


def _tup(i: int = 0) -> RelationTuple:
    return RelationTuple(
        namespace="n", object=f"o{i}", relation="view",
        subject=SubjectID(id="alice"),
    )


class TestFaultRegistry:
    def test_fire_consumes_armed_count(self):
        r = FaultRegistry()
        r.arm("x.y", times=2)
        with pytest.raises(FaultInjected, match="x.y"):
            r.fire("x.y")
        assert r.armed("x.y") == 1
        with pytest.raises(FaultInjected):
            r.fire("x.y")
        r.fire("x.y")  # disarmed: no-op
        assert r.fired("x.y") == 2

    def test_should_fire_is_the_non_raising_form(self):
        r = FaultRegistry()
        assert not r.should_fire("a")
        r.arm("a")
        assert r.should_fire("a")
        assert not r.should_fire("a")

    def test_env_knob(self):
        r = FaultRegistry(env={"KETO_FAULTS": "a.b, c.d:3 ,,"})
        assert r.armed("a.b") == 1
        assert r.armed("c.d") == 3

    def test_snapshot_load_roundtrip(self):
        r = FaultRegistry()
        r.arm("a", times=2)
        snap = r.snapshot()
        r2 = FaultRegistry()
        r2.arm("stale.fault")
        r2.load(snap)
        assert r2.armed("a") == 2
        assert r2.armed("stale.fault") == 0  # load replaces wholesale

    def test_arm_rejects_nonpositive(self):
        with pytest.raises(ValueError):
            FaultRegistry().arm("a", times=0)


class _OkEngine:
    def batch_check(self, requests, max_depth=0, depths=None):
        return [True] * len(requests)


class _GateEngine:
    """Blocks every batch on an event — makes queue states controllable."""

    def __init__(self):
        self.gate = threading.Event()
        self.calls = 0

    def batch_check(self, requests, max_depth=0, depths=None):
        self.calls += 1
        self.gate.wait(timeout=10)
        return [True] * len(requests)


class TestDispatcherWatchdog:
    def test_injected_death_restarts_dispatcher(self):
        m = MetricsRegistry()
        b = CheckBatcher(_OkEngine(), window_s=0, metrics=m)
        try:
            restarts = b._m_restarts
            FAULTS.arm("batcher.dispatcher_die")
            # the armed fault kills the dispatcher at its next loop top;
            # this check wakes it, gets answered, then the thread dies
            # and the watchdog replaces it
            assert b.check(_tup()) is True
            deadline = time.time() + 5
            while restarts.value < 1 and time.time() < deadline:
                time.sleep(0.01)
            assert restarts.value == 1
            # the replacement thread serves
            assert b.check(_tup(1)) is True
            assert FAULTS.fired("batcher.dispatcher_die") == 1
        finally:
            b.close()

    def test_inflight_futures_fail_typed_on_crash(self):
        class _Bomb(BaseException):  # escapes the per-batch engine guard
            pass

        class _BombEngine:
            def __init__(self):
                self.boom = True

            def batch_check(self, requests, max_depth=0, depths=None):
                if self.boom:
                    self.boom = False
                    raise _Bomb()
                return [True] * len(requests)

        b = CheckBatcher(_BombEngine(), window_s=0)
        try:
            with pytest.raises(DispatcherCrashed) as ei:
                b.check(_tup())
            assert ei.value.grpc_code == "INTERNAL"
            assert b.check(_tup(1)) is True  # watchdog restarted it
        finally:
            b.close()


class _FakeEncoded:
    """Stand-in for engine.device.EncodedBatch: just enough surface for
    the pipelined batcher (version/keys for the encoded cache, release for
    crash cleanup)."""

    version = 0

    def __init__(self, requests):
        self.requests = list(requests)
        self.released = False

    def keys(self):
        return [(r.object, 0, 0) for r in self.requests]

    def compact(self, keep):
        self.requests = [self.requests[i] for i in keep]

    def release(self):
        self.released = True


class _SplitEngine:
    """Minimal split encode/launch/decode engine: deterministic True
    answers, so the pipeline drills isolate STAGE failure handling from
    engine behavior."""

    def pipeline_supported(self):
        return True

    def encode_batch(self, requests, max_depth=0, depths=None):
        return _FakeEncoded(requests)

    def launch_encoded(self, enc):
        return enc

    def decode_launched(self, launched):
        return [True] * len(launched.requests)

    def batch_check(self, requests, max_depth=0, depths=None):
        return [True] * len(requests)


class TestPipelineStageWatchdog:
    """ISSUE-2 drills: a pipeline stage death (encode worker, decode
    thread) fails exactly the in-flight batch typed-retryable and the
    watchdog restarts the stage — queued work and the other stages keep
    serving."""

    def _pipelined(self, metrics=None):
        return CheckBatcher(
            _SplitEngine(),
            window_s=0,
            metrics=metrics,
            pipeline_depth=2,
            encode_workers=2,
        )

    @pytest.mark.parametrize(
        "site", ["batcher.encode_die", "batcher.decode_die"]
    )
    def test_stage_death_fails_inflight_typed_and_restarts(self, site):
        m = MetricsRegistry()
        b = self._pipelined(metrics=m)
        try:
            assert b.pipelined is True
            restarts = b._m_restarts
            FAULTS.arm(site)
            # the armed fault kills the stage while it HOLDS this batch:
            # the caller must get the typed retryable error, not a hang
            with pytest.raises(DispatcherCrashed) as ei:
                b.check(_tup(), timeout=10)
            assert ei.value.grpc_code == "INTERNAL"
            assert FAULTS.fired(site) == 1
            deadline = time.time() + 5
            while restarts.value < 1 and time.time() < deadline:
                time.sleep(0.01)
            assert restarts.value == 1
            # the restarted stage serves the next request
            assert b.check(_tup(1), timeout=10) is True
            # nothing leaked: the failed batch left the pipeline registry
            assert b.pipeline_stats()["batches_in_pipeline"] == 0
        finally:
            b.close()

    def test_stage_crash_releases_encoded_buffers(self):
        class _TrackingSplit(_SplitEngine):
            def encode_batch(self, requests, max_depth=0, depths=None):
                self.last_enc = _FakeEncoded(requests)
                return self.last_enc

        eng = _TrackingSplit()
        b = CheckBatcher(eng, window_s=0, pipeline_depth=2, encode_workers=1)
        try:
            FAULTS.arm("batcher.decode_die")
            with pytest.raises(DispatcherCrashed):
                b.check(_tup(), timeout=10)
            # the crash path returned the staging buffers to the pool
            # (enc.release) instead of leaking them until GC
            assert eng.last_enc.released is True
            assert b.check(_tup(1), timeout=10) is True
        finally:
            b.close()

    def test_pipelined_close_fails_stragglers_typed(self):
        class _StuckSplit(_SplitEngine):
            def __init__(self):
                self.gate = threading.Event()

            def decode_launched(self, launched):
                self.gate.wait(timeout=10)  # wedged device materialization
                return [True] * len(launched.requests)

        eng = _StuckSplit()
        b = CheckBatcher(
            eng, window_s=0, pipeline_depth=2, encode_workers=1
        )
        b.close_join_s = 0.2
        errs = []

        def call():
            try:
                b.check(_tup(), timeout=10)
            except Exception as e:
                errs.append(e)

        t = threading.Thread(target=call, daemon=True)
        t.start()
        deadline = time.time() + 5
        while not b.pipeline_stats()["batches_in_pipeline"] and (
            time.time() < deadline
        ):
            time.sleep(0.005)
        b.close()  # join budget expires; the held batch fails typed
        t.join(timeout=5)
        assert len(errs) == 1 and isinstance(errs[0], BatcherClosed)
        eng.gate.set()


class TestReconfigureDrill:
    """ISSUE-18 satellite: a LIVE ``reconfigure()`` (the autotuner's
    pipeline_depth/encode_workers seam) stalls in its drain window via
    the ``batcher.reconfigure_stall`` fault — concurrent traffic must
    neither error nor vanish: in-flight batches flush through the old
    stages, queued requests survive into the rebuilt pipeline."""

    def test_stalled_reconfigure_keeps_concurrent_traffic(self):
        b = CheckBatcher(
            _SplitEngine(), window_s=0, pipeline_depth=2, encode_workers=1
        )
        try:
            assert b.check(_tup()) is True  # pipeline warm
            FAULTS.arm_slow("batcher.reconfigure_stall", sleep_ms=150)
            results, errs = [], []

            def call(i):
                try:
                    results.append(b.check(_tup(i), timeout=10))
                except Exception as e:  # pragma: no cover - failure path
                    errs.append(e)

            threads = [
                threading.Thread(target=call, args=(i,), daemon=True)
                for i in range(8)
            ]
            for t in threads:
                t.start()
            # the reconfigure races the in-flight checks AND stalls in
            # its drain window while holding the quiesce flag
            assert b.reconfigure(pipeline_depth=3, encode_workers=2)
            for t in threads:
                t.join(timeout=10)
            assert FAULTS.fired("batcher.reconfigure_stall") == 1
            assert errs == []
            assert results == [True] * 8
            assert b.pipeline_depth == 3 and b.encode_workers == 2
            assert b.pipelined is True
            # the rebuilt pipeline serves fresh traffic
            assert b.check(_tup(99), timeout=10) is True
            assert b.pipeline_stats()["batches_in_pipeline"] == 0
        finally:
            b.close()


class TestLoadShedding:
    def test_queue_full_sheds_with_429_semantics(self):
        eng = _GateEngine()
        m = MetricsRegistry()
        b = CheckBatcher(eng, window_s=0, max_queue=1, metrics=m)
        try:
            t1 = threading.Thread(
                target=lambda: b.check(_tup()), daemon=True
            )
            t1.start()
            deadline = time.time() + 5
            while eng.calls < 1 and time.time() < deadline:
                time.sleep(0.005)  # first check is now IN FLIGHT
            t2 = threading.Thread(
                target=lambda: b.check(_tup(1)), daemon=True
            )
            t2.start()
            deadline = time.time() + 5
            while len(b._queue) < 1 and time.time() < deadline:
                time.sleep(0.005)  # second check is QUEUED: queue full
            with pytest.raises(BatcherOverloaded) as ei:
                b.check(_tup(2))
            assert ei.value.status_code == 429
            assert ei.value.grpc_code == "RESOURCE_EXHAUSTED"
            assert ei.value.retry_after_s >= 1
            assert b._m_shed.value == 1
            eng.gate.set()
            t1.join(timeout=5)
            t2.join(timeout=5)
        finally:
            eng.gate.set()
            b.close()


class TestBatcherClose:
    def test_check_after_close_raises_typed(self):
        b = CheckBatcher(_OkEngine(), window_s=0)
        b.close()
        with pytest.raises(BatcherClosed) as ei:
            b.check(_tup())
        assert ei.value.status_code == 503
        with pytest.raises(BatcherClosed):
            b.check_batch([_tup()])

    def test_close_fails_stuck_inflight_instead_of_hanging(self):
        eng = _GateEngine()  # never released: the sick-chip hang mode
        b = CheckBatcher(eng, window_s=0)
        b.close_join_s = 0.2
        errs = []

        def call():
            try:
                b.check(_tup())
            except Exception as e:
                errs.append(e)

        t = threading.Thread(target=call, daemon=True)
        t.start()
        deadline = time.time() + 5
        while eng.calls < 1 and time.time() < deadline:
            time.sleep(0.005)
        b.close()  # join budget 0.2s, then inflight is failed typed
        t.join(timeout=5)
        assert len(errs) == 1 and isinstance(errs[0], BatcherClosed)
        eng.gate.set()

    def test_close_drains_queue_when_engine_healthy(self):
        b = CheckBatcher(_OkEngine(), window_s=0)
        results = [b.check(_tup(i)) for i in range(4)]
        b.close()
        assert results == [True] * 4


class _FakeClock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t


class _FlakyPrimary:
    def __init__(self):
        self.fail = 0
        self.nan = 0
        self.calls = 0

    def batch_check(self, requests, max_depth=0, depths=None):
        self.calls += 1
        if self.fail > 0:
            self.fail -= 1
            raise RuntimeError("XLA compile blew up")
        if self.nan > 0:
            self.nan -= 1
            return [float("nan")] * len(requests)
        return [True] * len(requests)

    def wait_for_version(self, v, timeout_s=30.0):
        return True


class _Oracle:
    def __init__(self):
        self.calls = 0

    def batch_check(self, requests, max_depth=0):
        self.calls += 1
        return [False] * len(requests)

    def subject_is_allowed(self, requested, max_depth=0):
        self.calls += 1
        return False


class TestDeviceCircuitBreaker:
    def _breaker(self, primary, oracle, health=None, threshold=3):
        clock = _FakeClock()
        m = MetricsRegistry()
        eng = DeviceFallbackEngine(
            primary,
            fallback_factory=lambda: oracle,
            failure_threshold=threshold,
            cooldown_s=1.0,
            health=health,
            metrics=m,
            clock=clock,
        )
        return eng, clock, m

    def test_trips_after_threshold_and_serves_fallback(self):
        from keto_tpu.api.services import HealthServicer

        health = HealthServicer()
        health.set_serving(True)
        primary, oracle = _FlakyPrimary(), _Oracle()
        eng, clock, m = self._breaker(primary, oracle, health=health)
        primary.fail = 10
        for _ in range(2):
            assert eng.batch_check([_tup()]) == [False]  # oracle answers
            assert not eng.circuit_open()
            assert health.is_serving()
        assert eng.batch_check([_tup()]) == [False]  # third strike
        assert eng.circuit_open()
        assert not health.is_serving()  # degraded mode is visible
        # while open, the primary is not even consulted
        calls = primary.calls
        assert eng.batch_check([_tup()]) == [False]
        assert primary.calls == calls

    def test_nan_output_counts_as_failure(self):
        primary, oracle = _FlakyPrimary(), _Oracle()
        eng, clock, m = self._breaker(primary, oracle, threshold=1)
        primary.nan = 1
        assert eng.batch_check([_tup()]) == [False]  # validated, rejected
        assert eng.circuit_open()

    def test_halfopen_probe_recovers_and_restores_health(self):
        from keto_tpu.api.services import HealthServicer

        health = HealthServicer()
        health.set_serving(True)
        primary, oracle = _FlakyPrimary(), _Oracle()
        eng, clock, m = self._breaker(primary, oracle, health=health)
        primary.fail = 3
        for _ in range(3):
            eng.batch_check([_tup()])
        assert eng.circuit_open() and not health.is_serving()
        clock.t += 1.5  # past the cooldown: next batch is the probe
        assert eng.batch_check([_tup()]) == [True]  # primary healthy again
        assert not eng.circuit_open()
        assert health.is_serving()

    def test_failed_probe_reopens_with_backoff(self):
        primary, oracle = _FlakyPrimary(), _Oracle()
        eng, clock, m = self._breaker(primary, oracle)
        primary.fail = 4  # 3 to trip + 1 failed probe
        for _ in range(3):
            eng.batch_check([_tup()])
        clock.t += 1.5
        assert eng.batch_check([_tup()]) == [False]  # probe fails -> oracle
        assert eng.circuit_open()
        clock.t += 1.5  # doubled cooldown (2.0s): still open
        assert eng._use_primary() is False
        clock.t += 1.0  # now past it
        assert eng.batch_check([_tup()]) == [True]
        assert not eng.circuit_open()

    def test_injected_device_faults_reach_host_fallback_end_to_end(self):
        """The registry-wired path: device.compile_error and
        device.batch_nan (engine/device.py fault sites) degrade to the
        host oracle; answers stay correct throughout."""
        from keto_tpu.driver import Config, Registry

        cfg = Config(
            values={
                "namespaces": [{"id": 1, "name": "n"}],
                "log": {"level": "error"},
                "engine": {
                    "mode": "device",
                    "cache_size": 0,  # a cache hit would mask the faults
                    "encoded_cache_size": 0,  # ditto for the encoded cache
                    "fallback_threshold": 2,
                    "fallback_cooldown_ms": 50,
                },
            }
        )
        reg = Registry(cfg)
        reg.store().transact_relation_tuples([_tup()], [])
        checker = reg.checker()
        breaker = reg._engine_breaker
        assert isinstance(breaker, DeviceFallbackEngine)
        try:
            assert checker.check(_tup()) is True  # device path, healthy
            FAULTS.arm("device.compile_error", times=2)
            assert checker.check(_tup()) is True  # oracle keeps truth
            assert checker.check(_tup()) is True  # second strike: trips
            assert FAULTS.fired("device.compile_error") == 2
            assert breaker.circuit_open()
            assert not reg.health.is_serving()
            # the next device attempt is the half-open probe — make it hit
            # the OTHER failure class (garbage output, not an exception)
            FAULTS.arm("device.batch_nan", times=1)
            time.sleep(0.1)  # past the 50ms cooldown
            assert checker.check(_tup()) is True  # failed probe -> oracle
            assert FAULTS.fired("device.batch_nan") == 1
            assert breaker.circuit_open()  # reopened, cooldown doubled
            time.sleep(0.25)  # past the doubled (100ms) cooldown
            assert checker.check(_tup()) is True  # probe succeeds
            assert not breaker.circuit_open()
            assert reg.health.is_serving()
        finally:
            checker.close()


class TestClientRetry:
    def test_backoff_schedule_with_jitter_floor(self):
        from keto_tpu.client.retry import RetryPolicy, run_with_retry

        sleeps = []
        p = RetryPolicy(
            max_attempts=4, base_delay_s=0.1, multiplier=2.0,
            jitter=0.5, sleep=sleeps.append, rand=lambda: 0.0,
        )
        calls = []

        def attempt(remaining):
            calls.append(remaining)
            if len(calls) < 4:
                raise ConnectionError("down")
            return "ok"

        assert (
            run_with_retry(attempt, p, lambda e: True, timeout=None) == "ok"
        )
        # rand()=0 -> the jitter FLOOR: half the nominal delay each time
        assert sleeps == pytest.approx([0.05, 0.1, 0.2])

    def test_attempts_exhaust(self):
        from keto_tpu.client.retry import RetryPolicy, run_with_retry

        p = RetryPolicy(max_attempts=2, sleep=lambda s: None)
        with pytest.raises(ConnectionError):
            run_with_retry(
                self._always_fail, p, lambda e: True, timeout=None
            )

    @staticmethod
    def _always_fail(remaining):
        raise ConnectionError("down")

    def test_non_retryable_raises_immediately(self):
        from keto_tpu.client.retry import RetryPolicy, run_with_retry

        calls = []

        def attempt(remaining):
            calls.append(1)
            raise ValueError("bad request")

        with pytest.raises(ValueError):
            run_with_retry(
                attempt,
                RetryPolicy(sleep=lambda s: None),
                lambda e: isinstance(e, ConnectionError),
                timeout=None,
            )
        assert len(calls) == 1

    def test_deadline_is_honored_end_to_end(self):
        from keto_tpu.client.retry import RetryPolicy, run_with_retry

        clock = _FakeClock()
        slept = []

        def sleep(s):
            slept.append(s)
            clock.t += s

        p = RetryPolicy(
            max_attempts=10, base_delay_s=0.4, jitter=0.0, sleep=sleep
        )
        remainders = []

        def attempt(remaining):
            remainders.append(remaining)
            clock.t += 0.1  # each attempt costs 100ms
            raise ConnectionError("down")

        with pytest.raises(ConnectionError):
            run_with_retry(
                attempt, p, lambda e: True, timeout=1.0, clock=clock
            )
        # attempts see a SHRINKING budget, and the loop stops as soon as
        # the next backoff would cross the deadline — well before 10 tries
        assert remainders[0] == pytest.approx(1.0)
        assert all(
            a > b for a, b in zip(remainders, remainders[1:])
        )
        assert len(remainders) < 10
        assert clock.t - 100.0 <= 1.0 + 1e-6

    def test_grpc_code_matching_and_call_wiring(self):
        import grpc

        from keto_tpu.client import GrpcClient, RetryPolicy
        from keto_tpu.client.retry import grpc_retryable

        class _Code:
            def __init__(self, name):
                self.name = name

        class _Rpc(grpc.RpcError):
            def __init__(self, name):
                self._name = name

            def code(self):
                return _Code(self._name)

        assert grpc_retryable(_Rpc("UNAVAILABLE"))
        assert grpc_retryable(_Rpc("RESOURCE_EXHAUSTED"))
        assert not grpc_retryable(_Rpc("INVALID_ARGUMENT"))
        assert not grpc_retryable(ValueError("x"))

        client = GrpcClient(
            "127.0.0.1:1",
            retry=RetryPolicy(max_attempts=3, sleep=lambda s: None),
        )
        try:
            outcomes = [_Rpc("UNAVAILABLE"), _Rpc("RESOURCE_EXHAUSTED")]

            def rpc(request, timeout=None):
                if outcomes:
                    raise outcomes.pop(0)
                return "answer"

            assert client._call(rpc, object(), timeout=5.0) == "answer"

            def rpc_fatal(request, timeout=None):
                raise _Rpc("INVALID_ARGUMENT")

            with pytest.raises(grpc.RpcError):
                client._call(rpc_fatal, object(), timeout=5.0)
        finally:
            client.close()

    def test_rest_client_retries_shed_and_unavailable(self):
        from keto_tpu.client import RestClient, RetryPolicy

        codes = iter([429, 503, 200])
        seen = []

        def handler(request):
            code = next(codes)
            seen.append(code)
            if code != 200:
                return httpx.Response(
                    code,
                    json={"error": {"code": code, "message": "busy"}},
                    headers={"Retry-After": "1"},
                )
            return httpx.Response(200, json={"allowed": True})

        client = RestClient(
            "http://test",
            transport=httpx.MockTransport(handler),
            retry=RetryPolicy(max_attempts=4, sleep=lambda s: None),
        )
        try:
            assert client.check(_tup()).allowed is True
            assert seen == [429, 503, 200]
        finally:
            client.close()

    def test_rest_client_does_not_retry_client_errors(self):
        from keto_tpu.client import RestClient, RetryPolicy
        from keto_tpu.utils.errors import ErrMalformedInput

        calls = []

        def handler(request):
            calls.append(1)
            return httpx.Response(
                400, json={"error": {"code": 400, "message": "nope"}}
            )

        client = RestClient(
            "http://test",
            transport=httpx.MockTransport(handler),
            retry=RetryPolicy(max_attempts=4, sleep=lambda s: None),
        )
        try:
            with pytest.raises(ErrMalformedInput):
                client.check(_tup())
            assert len(calls) == 1
        finally:
            client.close()


# -- replica pool fault drills (integration) --------------------------------


def _pool_config():
    from keto_tpu.driver import Config

    return Config(
        values={
            "namespaces": [{"id": 1, "name": "n"}],
            "log": {"level": "error"},
            "serve": {
                "read": {"port": 0, "host": "127.0.0.1", "workers": 3},
                "write": {"port": 0, "host": "127.0.0.1"},
            },
        }
    )


@pytest.fixture()
def pool():
    from keto_tpu.driver import Registry

    reg = Registry(_pool_config())
    loop = asyncio.new_event_loop()
    threading.Thread(target=loop.run_forever, daemon=True).start()
    rp, wp = asyncio.run_coroutine_threadsafe(
        reg.start_all(), loop
    ).result(timeout=120)
    yield reg, rp, wp
    asyncio.run_coroutine_threadsafe(reg.stop_all(), loop).result(
        timeout=30
    )
    loop.call_soon_threadsafe(loop.stop)


def _converges(rp, params, want_status, tries=24, timeout=120.0):
    """Fresh connection per probe: SO_REUSEPORT spreads them over the
    replicas, so `tries` consecutive agreements cover the whole pool."""
    deadline = time.time() + timeout
    streak = 0
    while streak < tries and time.time() < deadline:
        try:
            r = httpx.get(
                f"http://127.0.0.1:{rp}/check", params=params, timeout=10
            )
            status = r.status_code
        except httpx.TransportError:
            status = -1  # replica churn mid-probe: keep probing
        if status == want_status:
            streak += 1
        else:
            streak = 0
            time.sleep(0.05)
    return streak >= tries


def _put(wp, tup):
    r = httpx.put(f"http://127.0.0.1:{wp}/relation-tuples", json=tup)
    assert r.status_code == 201


def _wait_children(pool_obj, n, timeout=30.0, dead=()):
    """Until the pool has n live children, none of them in `dead` — the
    latter matters right after a kill, when the supervisor may not have
    pruned the victim yet and the old link set still looks healthy."""
    dead = set(dead)
    deadline = time.time() + timeout
    while time.time() < deadline:
        links = list(pool_obj._children)
        pids = [l.pid for l in links]
        if (
            len(links) == n
            and all(p > 0 for p in pids)
            and not dead.intersection(pids)
        ):
            return links
        time.sleep(0.05)
    raise AssertionError(
        f"pool never reached {n} live children (dead={dead}): "
        f"{[l.pid for l in pool_obj._children]}"
    )


class TestReplicaSelfHealing:
    def test_sigkill_respawn_and_resync(self, pool):
        reg, rp, wp = pool
        pool_obj = reg._replica_pool
        assert pool_obj is not None
        links = _wait_children(pool_obj, 2)
        old_pids = {l.pid for l in links}

        # a write BEFORE the kill: the respawned replica must know it
        before = {
            "namespace": "n", "object": "pre", "relation": "view",
            "subject_id": "alice",
        }
        _put(wp, before)
        assert _converges(rp, before, 200)

        victim = links[0].pid
        os.kill(victim, signal.SIGKILL)
        # supervisor heals the pool: victim pruned, replacement spawned
        links = _wait_children(pool_obj, 2, dead={victim})
        new_pids = {l.pid for l in links}
        assert new_pids != old_pids

        # a write AFTER the respawn: the delta stream + resync handshake
        # must reach the replacement too
        after = {
            "namespace": "n", "object": "post", "relation": "view",
            "subject_id": "alice",
        }
        _put(wp, after)
        assert _converges(rp, after, 200)
        assert _converges(rp, before, 200)
        m = reg.metrics()
        assert m._metrics["keto_replica_respawns_total"].value >= 1

    def test_delta_drop_resync_refills_the_gap(self, pool):
        reg, rp, wp = pool
        pool_obj = reg._replica_pool
        _wait_children(pool_obj, 2)
        # drop exactly one frame to one replica: a silent version gap
        FAULTS.arm("delta.drop")
        dropped = {
            "namespace": "n", "object": "dropped", "relation": "view",
            "subject_id": "alice",
        }
        _put(wp, dropped)
        assert FAULTS.fired("delta.drop") == 1
        # the NEXT write arrives out of order at the gapped replica,
        # triggering its resync request; the parent replays the log
        trailer = {
            "namespace": "n", "object": "trailer", "relation": "view",
            "subject_id": "alice",
        }
        _put(wp, trailer)
        assert _converges(rp, dropped, 200)
        assert _converges(rp, trailer, 200)
        m = reg.metrics()
        assert m._metrics["keto_replica_resyncs_total"].value >= 1

    def test_inherited_replica_crash_fault_heals(self):
        """replica.crash armed BEFORE the fork is inherited by every
        replica (each crashes applying its first delta); disarming in the
        parent means respawns — which carry the parent's current fault
        snapshot — come back clean. The pool heals without intervention."""
        from keto_tpu.driver import Registry

        FAULTS.arm("replica.crash")  # inherited at fork by both children
        reg = Registry(_pool_config())
        loop = asyncio.new_event_loop()
        threading.Thread(target=loop.run_forever, daemon=True).start()
        try:
            rp, wp = asyncio.run_coroutine_threadsafe(
                reg.start_all(), loop
            ).result(timeout=120)
            pool_obj = reg._replica_pool
            old = {l.pid for l in _wait_children(pool_obj, 2)}
            # parent disarms: respawn commands ship a CLEAN snapshot
            FAULTS.disarm("replica.crash")
            tup = {
                "namespace": "n", "object": "doc", "relation": "view",
                "subject_id": "alice",
            }
            _put(wp, tup)  # both replicas crash applying this delta
            links = _wait_children(pool_obj, 2, timeout=60, dead=old)
            assert {l.pid for l in links} != old
            assert _converges(rp, tup, 200)
        finally:
            asyncio.run_coroutine_threadsafe(reg.stop_all(), loop).result(
                timeout=30
            )
            loop.call_soon_threadsafe(loop.stop)


class TestShedAtTheTransports:
    def test_rest_maps_shed_to_429_with_retry_after(self):
        """BatcherOverloaded -> HTTP 429 + Retry-After via the REST error
        middleware mapping."""
        from keto_tpu.api.rest import _json_error

        resp = _json_error(BatcherOverloaded())
        assert resp.status == 429
        assert resp.headers["Retry-After"] == "1"

    def test_rest_maps_closed_to_503_with_retry_after(self):
        from keto_tpu.api.rest import _json_error

        resp = _json_error(BatcherClosed())
        assert resp.status == 503
        assert resp.headers["Retry-After"] == "1"

    def test_grpc_abort_carries_resource_exhausted(self):
        import grpc

        from keto_tpu.api.services import _abort

        class _Ctx:
            def __init__(self):
                self.trailing = None
                self.code = None
                self.details = None

            def set_trailing_metadata(self, md):
                self.trailing = md

            def abort(self, code, details):
                self.code = code
                self.details = details
                raise RuntimeError("aborted")  # grpc aborts by raising

        ctx = _Ctx()
        with pytest.raises(RuntimeError):
            _abort(ctx, BatcherOverloaded())
        assert ctx.code == grpc.StatusCode.RESOURCE_EXHAUSTED
        assert ("retry-after", "1") in tuple(ctx.trailing)
