"""Packed (bitpacked DMA) check path: DeviceCheckEngine(mode="packed") must
agree bit-for-bit with the host oracle and the scatter path on every
scenario — including the unknown-node depth-0 contract. On the CPU test
backend the Pallas kernel runs in interpret mode; on TPU it compiles to
Mosaic (the bench exercises that path)."""

import numpy as np
import pytest

from keto_tpu.engine import CheckEngine
from keto_tpu.engine.device import DeviceCheckEngine
from keto_tpu.graph import SnapshotManager
from keto_tpu.relationtuple import RelationTuple
from keto_tpu.store import InMemoryTupleStore

from test_closure_engine import _random_requests
from test_device_engines import random_store


def t(s: str) -> RelationTuple:
    return RelationTuple.from_string(s)


def make_packed(store, max_depth=5):
    mgr = SnapshotManager(store)
    return DeviceCheckEngine(mgr, max_depth=max_depth, mode="packed")


class TestPackedScenarios:
    def test_direct_and_indirect(self):
        store = InMemoryTupleStore()
        store.write_relation_tuples(
            t("n:obj#access@(n:org#member)"),
            t("n:org#member@(n:team#member)"),
            t("n:team#member@alice"),
            t("n:doc#read@bob"),
        )
        eng = make_packed(store)
        assert eng.subject_is_allowed(t("n:obj#access@alice"))
        assert eng.subject_is_allowed(t("n:doc#read@bob"))
        assert not eng.subject_is_allowed(t("n:obj#access@bob"))
        assert not eng.subject_is_allowed(t("n:doc#read@alice"))

    def test_unknown_nodes_denied(self):
        """The dummy row is shared by unknown starts AND unknown targets;
        without the depth-0 forcing an unknown start would 'reach' an
        unknown target through it (ops/packed.py docstring contract)."""
        store = InMemoryTupleStore()
        store.write_relation_tuples(t("n:obj#r@alice"))
        eng = make_packed(store)
        assert not eng.subject_is_allowed(t("no:thing#here@nobody"))
        assert not eng.subject_is_allowed(t("n:obj#r@nobody"))
        assert not eng.subject_is_allowed(t("no:thing#here@alice"))

    def test_depth_budget(self):
        store = InMemoryTupleStore()
        store.write_relation_tuples(
            t("n:obj#r@(n:s1#m)"),
            t("n:s1#m@(n:s2#m)"),
            t("n:s2#m@alice"),
        )
        eng = make_packed(store, max_depth=10)
        req = t("n:obj#r@alice")
        assert not eng.subject_is_allowed(req, max_depth=2)
        assert eng.subject_is_allowed(req, max_depth=3)

    def test_start_equals_target_needs_real_path(self):
        """set@same-set is only allowed through an actual cycle — the
        start bit itself is dist 0 and must not satisfy the probe."""
        store = InMemoryTupleStore()
        store.write_relation_tuples(t("n:obj#r@alice"))
        eng = make_packed(store)
        assert not eng.subject_is_allowed(t("n:obj#r@(n:obj#r)"))

    def test_exact_depth_boundary(self):
        """A path of length d must be allowed at depth d and denied at
        d-1 — the probe-lag compensation boundary."""
        store = InMemoryTupleStore()
        store.write_relation_tuples(t("n:a#r@(n:b#r)"), t("n:b#r@u"))
        eng = make_packed(store, max_depth=2)
        # budget == global max == path length: needs the extra iteration
        assert eng.subject_is_allowed(t("n:a#r@u"), max_depth=2)
        assert not eng.subject_is_allowed(t("n:a#r@u"), max_depth=1)

    def test_cycles_terminate(self):
        store = InMemoryTupleStore()
        store.write_relation_tuples(
            t("n:a#r@(n:b#r)"), t("n:b#r@(n:a#r)")
        )
        eng = make_packed(store)
        assert not eng.subject_is_allowed(t("n:a#r@alice"))
        assert eng.subject_is_allowed(t("n:a#r@(n:a#r)"))

    def test_write_visibility(self):
        store = InMemoryTupleStore()
        eng = make_packed(store)
        req = t("n:obj#r@alice")
        assert not eng.subject_is_allowed(req)
        store.write_relation_tuples(req)
        assert eng.subject_is_allowed(req)


class TestPackedMatchesOracle:
    @pytest.mark.parametrize("seed", range(3))
    def test_random_graphs(self, seed):
        rng = np.random.default_rng(seed + 400)
        store = random_store(rng, n_objects=12, n_users=8, n_edges=90)
        host = CheckEngine(store, max_depth=5)
        eng = make_packed(store, max_depth=5)
        reqs = _random_requests(rng, 12, 8, k=48)
        expect = [host.subject_is_allowed(r) for r in reqs]
        assert eng.batch_check(reqs) == expect

    def test_per_request_depths(self):
        rng = np.random.default_rng(77)
        store = random_store(rng, n_objects=10, n_users=6, n_edges=70)
        host = CheckEngine(store, max_depth=8)
        eng = make_packed(store, max_depth=8)
        reqs = _random_requests(rng, 10, 6, k=32)
        depths = [int(rng.integers(1, 9)) for _ in reqs]
        expect = [
            host.subject_is_allowed(r, max_depth=d)
            for r, d in zip(reqs, depths)
        ]
        assert eng.batch_check(reqs, depths=depths) == expect
