"""Domain-model tests mirroring reference internal/relationtuple tests:
string grammar round-trips, subject parsing, query matching."""

import pytest

from keto_tpu.relationtuple import (
    RelationQuery,
    RelationTuple,
    SubjectID,
    SubjectSet,
    parse_tuples_text,
    subject_from_string,
)
from keto_tpu.utils import ErrMalformedInput


class TestSubjectGrammar:
    def test_plain_id(self):
        assert subject_from_string("user1") == SubjectID(id="user1")

    def test_subject_set(self):
        assert subject_from_string("ns:obj#rel") == SubjectSet(
            namespace="ns", object="obj", relation="rel"
        )

    def test_string_roundtrip(self):
        for s in ["user1", "ns:obj#rel", "n:o#"]:
            assert str(subject_from_string(s)) == s

    def test_hash_means_subject_set(self):
        # '#'-detection: reference definitions.go:137-142; a '#' without a
        # ':' cannot form a valid subject set
        with pytest.raises(ErrMalformedInput):
            subject_from_string("obj#rel")


class TestTupleGrammar:
    def test_parse_simple(self):
        t = RelationTuple.from_string("n:o#r@s")
        assert t == RelationTuple("n", "o", "r", SubjectID("s"))

    def test_parse_subject_set(self):
        t = RelationTuple.from_string("n:o#r@n2:o2#r2")
        assert t.subject == SubjectSet("n2", "o2", "r2")

    def test_parse_parenthesized_subject_set(self):
        t = RelationTuple.from_string("n:o#r@(n2:o2#r2)")
        assert t.subject == SubjectSet("n2", "o2", "r2")

    def test_split_on_first_separator(self):
        # splits at the FIRST ':', '#', '@' (reference definitions.go:276-305)
        t = RelationTuple.from_string("n:o:x#r@s")
        assert t.namespace == "n" and t.object == "o:x"

    def test_malformed(self):
        for s in ["no-colon", "n:no-hash", "n:o#no-at"]:
            with pytest.raises(ErrMalformedInput):
                RelationTuple.from_string(s)

    def test_string_roundtrip(self):
        for s in ["n:o#r@s", "n:o#r@n2:o2#r2"]:
            assert str(RelationTuple.from_string(s)) == s

    def test_json_roundtrip(self):
        for t in [
            RelationTuple("n", "o", "r", SubjectID("s")),
            RelationTuple("n", "o", "r", SubjectSet("a", "b", "c")),
        ]:
            assert RelationTuple.from_dict(t.to_dict()) == t

    def test_parse_text_with_comments(self):
        text = """
        // a comment
        n:o#r@s

        n:o#r@x // trailing
        """
        ts = parse_tuples_text(text)
        assert [str(t) for t in ts] == ["n:o#r@s", "n:o#r@x"]


class TestRelationQuery:
    def setup_method(self):
        self.t = RelationTuple("n", "o", "r", SubjectID("s"))

    def test_wildcards(self):
        assert RelationQuery().matches(self.t)
        assert RelationQuery(namespace="n").matches(self.t)
        assert not RelationQuery(namespace="m").matches(self.t)
        assert RelationQuery(namespace="n", object="o", relation="r").matches(self.t)
        assert not RelationQuery(subject=SubjectID("z")).matches(self.t)
        assert RelationQuery(subject=SubjectID("s")).matches(self.t)

    def test_subject_set_query(self):
        t = RelationTuple("n", "o", "r", SubjectSet("a", "b", "c"))
        assert RelationQuery(subject=SubjectSet("a", "b", "c")).matches(t)
        assert not RelationQuery(subject=SubjectID("a")).matches(t)

    def test_dict_roundtrip(self):
        q = RelationQuery(namespace="n", subject=SubjectSet("a", "b", "c"))
        assert RelationQuery.from_dict(q.to_dict()) == q
