"""Columnar-store specifics: bulk ingest, snapshot_ids fast path, engines
over pre-encoded columns. (The full Manager contract suite in test_store.py
already runs against this backend via the parametrized `store` fixture.)"""

import numpy as np
import pytest

from keto_tpu.engine import CheckEngine
from keto_tpu.engine.closure import ClosureCheckEngine
from keto_tpu.graph import SnapshotManager
from keto_tpu.relationtuple import RelationQuery, RelationTuple, SubjectID
from keto_tpu.store import ColumnarTupleStore


def t(s):
    return RelationTuple.from_string(s)


class TestBulkLoad:
    def test_bulk_then_queries(self):
        s = ColumnarTupleStore()
        src = [("n", f"o{i}", "r") for i in range(100)]
        dst = [(f"u{i % 7}",) for i in range(100)]
        s.bulk_load_edges(src, dst)
        assert len(s) == 100
        page, token = s.get_relation_tuples(
            RelationQuery(namespace="n", object="o3")
        )
        assert len(page) == 1
        assert page[0].subject == SubjectID("u3")
        # subject filter
        page, _ = s.get_relation_tuples(
            RelationQuery(subject=SubjectID("u0"))
        )
        assert len(page) == 15  # u0 for i = 0, 7, 14, ..., 98

    def test_bulk_mixed_subject_kinds(self):
        s = ColumnarTupleStore()
        s.bulk_load_edges(
            [("n", "doc", "view"), ("n", "grp", "m")],
            [("n", "grp", "m"), ("alice",)],
        )
        tuples = s.all_tuples()
        assert t("n:doc#view@(n:grp#m)") in tuples
        assert t("n:grp#m@alice") in tuples

    def test_snapshot_ids_zero_object_path(self):
        s = ColumnarTupleStore()
        s.bulk_load_edges(
            [("n", "a", "r"), ("n", "b", "r")], [("u1",), ("u2",)]
        )
        src, dst, vocab, version = s.snapshot_ids()
        assert len(src) == len(dst) == 2
        assert version == 1
        assert vocab.key(int(src[0])) == ("n", "a", "r")
        assert vocab.key(int(dst[0])) == ("u1",)

    def test_snapshot_manager_sees_bulk_load(self):
        s = ColumnarTupleStore()
        mgr = SnapshotManager(s)
        assert mgr.snapshot().num_edges == 0
        s.bulk_load_edges([("n", "a", "r")], [("u1",)])
        snap = mgr.snapshot()
        assert snap.num_edges == 1

    def test_bulk_duplicates_deduped_and_deletable(self):
        """Duplicate pairs in the bulk input (and re-loads of existing
        pairs) must collapse to one live row, so a later delete fully
        revokes the grant — no ghost edges."""
        s = ColumnarTupleStore()
        s.bulk_load_edges(
            [("n", "a", "r"), ("n", "a", "r"), ("n", "b", "r")],
            [("u1",), ("u1",), ("u2",)],
        )
        assert len(s) == 2
        s.bulk_load_edges([("n", "a", "r")], [("u1",)])  # re-load existing
        assert len(s) == 2
        mgr = SnapshotManager(s)
        assert mgr.snapshot().num_edges == 2
        s.delete_relation_tuples(t("n:a#r@u1"))
        assert len(s) == 1
        assert mgr.snapshot().num_edges == 1
        page, _ = s.get_relation_tuples(RelationQuery(namespace="n", object="a"))
        assert page == []

    def test_delete_after_bulk_visible_in_snapshot(self):
        s = ColumnarTupleStore()
        s.bulk_load_edges([("n", "a", "r"), ("n", "b", "r")], [("u1",), ("u2",)])
        mgr = SnapshotManager(s)
        assert mgr.snapshot().num_edges == 2
        s.delete_relation_tuples(t("n:a#r@u1"))
        assert mgr.snapshot().num_edges == 1
        assert len(s) == 1


class TestEnginesOverColumnar:
    @pytest.mark.parametrize("seed", range(2))
    def test_closure_matches_host_oracle(self, seed):
        rng = np.random.default_rng(seed + 400)
        s = ColumnarTupleStore()
        n_obj, n_usr = 20, 12
        src, dst = [], []
        for _ in range(200):
            src.append((f"n", f"o{rng.integers(n_obj)}", f"r{rng.integers(3)}"))
            if rng.random() < 0.45:
                dst.append(
                    ("n", f"o{rng.integers(n_obj)}", f"r{rng.integers(3)}")
                )
            else:
                dst.append((f"u{rng.integers(n_usr)}",))
        s.bulk_load_edges(src, dst)
        host = CheckEngine(s, max_depth=5)
        eng = ClosureCheckEngine(SnapshotManager(s), max_depth=5)
        reqs = []
        for _ in range(64):
            obj = f"o{rng.integers(n_obj)}"
            rel = f"r{rng.integers(3)}"
            if rng.random() < 0.3:
                sub = f"n:o{rng.integers(n_obj)}#r{rng.integers(3)}"
            else:
                sub = f"u{rng.integers(n_usr)}"
            reqs.append(t(f"n:{obj}#{rel}@({sub})"))
        expect = [host.subject_is_allowed(r) for r in reqs]
        assert eng.batch_check(reqs) == expect

    def test_incremental_write_after_bulk(self):
        s = ColumnarTupleStore()
        s.bulk_load_edges([("n", "doc", "view")], [("n", "grp", "m")])
        eng = ClosureCheckEngine(SnapshotManager(s), max_depth=5)
        req = t("n:doc#view@alice")
        assert not eng.subject_is_allowed(req)
        s.write_relation_tuples(t("n:grp#m@alice"))
        assert eng.subject_is_allowed(req)
        s.delete_relation_tuples(t("n:grp#m@alice"))
        assert not eng.subject_is_allowed(req)
