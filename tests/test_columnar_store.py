"""Columnar-store specifics: bulk ingest, snapshot_ids fast path, engines
over pre-encoded columns. (The full Manager contract suite in test_store.py
already runs against this backend via the parametrized `store` fixture.)"""

import time

import numpy as np
import pytest

from keto_tpu.engine import CheckEngine
from keto_tpu.engine.closure import ClosureCheckEngine
from keto_tpu.graph import SnapshotManager
from keto_tpu.relationtuple import RelationQuery, RelationTuple, SubjectID
from keto_tpu.store import ColumnarTupleStore


def t(s):
    return RelationTuple.from_string(s)


class TestBulkLoad:
    def test_bulk_then_queries(self):
        s = ColumnarTupleStore()
        src = [("n", f"o{i}", "r") for i in range(100)]
        dst = [(f"u{i % 7}",) for i in range(100)]
        s.bulk_load_edges(src, dst)
        assert len(s) == 100
        page, token = s.get_relation_tuples(
            RelationQuery(namespace="n", object="o3")
        )
        assert len(page) == 1
        assert page[0].subject == SubjectID("u3")
        # subject filter
        page, _ = s.get_relation_tuples(
            RelationQuery(subject=SubjectID("u0"))
        )
        assert len(page) == 15  # u0 for i = 0, 7, 14, ..., 98

    def test_bulk_mixed_subject_kinds(self):
        s = ColumnarTupleStore()
        s.bulk_load_edges(
            [("n", "doc", "view"), ("n", "grp", "m")],
            [("n", "grp", "m"), ("alice",)],
        )
        tuples = s.all_tuples()
        assert t("n:doc#view@(n:grp#m)") in tuples
        assert t("n:grp#m@alice") in tuples

    def test_snapshot_ids_zero_object_path(self):
        s = ColumnarTupleStore()
        s.bulk_load_edges(
            [("n", "a", "r"), ("n", "b", "r")], [("u1",), ("u2",)]
        )
        src, dst, vocab, version = s.snapshot_ids()
        assert len(src) == len(dst) == 2
        assert version == 1
        assert vocab.key(int(src[0])) == ("n", "a", "r")
        assert vocab.key(int(dst[0])) == ("u1",)

    def test_snapshot_manager_sees_bulk_load(self):
        s = ColumnarTupleStore()
        mgr = SnapshotManager(s)
        assert mgr.snapshot().num_edges == 0
        s.bulk_load_edges([("n", "a", "r")], [("u1",)])
        snap = mgr.snapshot()
        assert snap.num_edges == 1

    def test_bulk_duplicates_deduped_and_deletable(self):
        """Duplicate pairs in the bulk input (and re-loads of existing
        pairs) must collapse to one live row, so a later delete fully
        revokes the grant — no ghost edges."""
        s = ColumnarTupleStore()
        s.bulk_load_edges(
            [("n", "a", "r"), ("n", "a", "r"), ("n", "b", "r")],
            [("u1",), ("u1",), ("u2",)],
        )
        assert len(s) == 2
        s.bulk_load_edges([("n", "a", "r")], [("u1",)])  # re-load existing
        assert len(s) == 2
        mgr = SnapshotManager(s)
        assert mgr.snapshot().num_edges == 2
        s.delete_relation_tuples(t("n:a#r@u1"))
        assert len(s) == 1
        assert mgr.snapshot().num_edges == 1
        page, _ = s.get_relation_tuples(RelationQuery(namespace="n", object="a"))
        assert page == []

    def test_delete_after_bulk_visible_in_snapshot(self):
        s = ColumnarTupleStore()
        s.bulk_load_edges([("n", "a", "r"), ("n", "b", "r")], [("u1",), ("u2",)])
        mgr = SnapshotManager(s)
        assert mgr.snapshot().num_edges == 2
        s.delete_relation_tuples(t("n:a#r@u1"))
        assert mgr.snapshot().num_edges == 1
        assert len(s) == 1


class TestEnginesOverColumnar:
    @pytest.mark.parametrize("seed", range(2))
    def test_closure_matches_host_oracle(self, seed):
        rng = np.random.default_rng(seed + 400)
        s = ColumnarTupleStore()
        n_obj, n_usr = 20, 12
        src, dst = [], []
        for _ in range(200):
            src.append((f"n", f"o{rng.integers(n_obj)}", f"r{rng.integers(3)}"))
            if rng.random() < 0.45:
                dst.append(
                    ("n", f"o{rng.integers(n_obj)}", f"r{rng.integers(3)}")
                )
            else:
                dst.append((f"u{rng.integers(n_usr)}",))
        s.bulk_load_edges(src, dst)
        host = CheckEngine(s, max_depth=5)
        eng = ClosureCheckEngine(SnapshotManager(s), max_depth=5)
        reqs = []
        for _ in range(64):
            obj = f"o{rng.integers(n_obj)}"
            rel = f"r{rng.integers(3)}"
            if rng.random() < 0.3:
                sub = f"n:o{rng.integers(n_obj)}#r{rng.integers(3)}"
            else:
                sub = f"u{rng.integers(n_usr)}"
            reqs.append(t(f"n:{obj}#{rel}@({sub})"))
        expect = [host.subject_is_allowed(r) for r in reqs]
        assert eng.batch_check(reqs) == expect

    def test_incremental_write_after_bulk(self):
        s = ColumnarTupleStore()
        s.bulk_load_edges([("n", "doc", "view")], [("n", "grp", "m")])
        eng = ClosureCheckEngine(SnapshotManager(s), max_depth=5)
        req = t("n:doc#view@alice")
        assert not eng.subject_is_allowed(req)
        s.write_relation_tuples(t("n:grp#m@alice"))
        assert eng.subject_is_allowed(req)
        s.delete_relation_tuples(t("n:grp#m@alice"))
        assert not eng.subject_is_allowed(req)


class TestChunkedRowIndex:
    """Point ops after bulk loads must work WITHOUT materializing a full
    row dict (the sorted-chunk + overlay scheme), across every
    delete/re-add interleaving."""

    def test_point_write_after_bulk_is_immediate(self):
        store = ColumnarTupleStore()
        src = [("n", f"o{i}", "r") for i in range(5000)]
        dst = [(f"u{i}",) for i in range(5000)]
        store.bulk_load_edges(src, dst)
        store.write_relation_tuples(t("n:fresh#r@alice"))
        assert len(store) == 5001
        # the structural invariant behind "no rebuild stall": a point
        # write must NOT materialize the bulk rows into the overlay dict
        # (the eager rebuild would put all 5000 there)
        assert len(store._row_of) == 1
        # duplicate of a bulk-loaded row stays idempotent
        store.write_relation_tuples(t("n:o17#r@u17"))
        assert len(store) == 5001
        assert len(store._row_of) == 1

    def test_delete_bulk_row_then_readd_via_bulk_and_point(self):
        store = ColumnarTupleStore()
        store.bulk_load_edges([("n", "a", "r")], [("u1",)])
        store.delete_relation_tuples(t("n:a#r@u1"))
        assert len(store) == 0
        # re-add through another bulk load: dedup must see the tombstone
        store.bulk_load_edges([("n", "a", "r")], [("u1",)])
        assert len(store) == 1
        # point delete of the re-added row (owner = highest row)
        store.delete_relation_tuples(t("n:a#r@u1"))
        assert len(store) == 0
        # point re-add, then bulk re-add is deduped against the overlay
        store.write_relation_tuples(t("n:a#r@u1"))
        store.bulk_load_edges([("n", "a", "r")], [("u1",)])
        assert len(store) == 1

    def test_point_then_delete_then_bulk_then_point(self):
        """The adversarial chain: overlay row dies, bulk re-adds, point
        insert must see the bulk row as the live owner (max-row rule)."""
        store = ColumnarTupleStore()
        store.write_relation_tuples(t("n:x#r@u"))
        store.delete_relation_tuples(t("n:x#r@u"))
        store.bulk_load_edges([("n", "x", "r")], [("u",)])
        assert len(store) == 1
        store.write_relation_tuples(t("n:x#r@u"))  # duplicate: no-op
        assert len(store) == 1
        tuples, _ = store.get_relation_tuples(RelationQuery(namespace="n"))
        assert len(tuples) == 1

    def test_chunk_compaction_keeps_current_owner(self):
        store = ColumnarTupleStore()
        # >32 bulk loads forces compaction; key "n:k#r@u" cycles
        # delete/re-add so duplicates exist across chunks
        for i in range(40):
            store.bulk_load_edges(
                [("n", f"k{i}", "r"), ("n", "cycled", "r")],
                [(f"u{i}",), ("u",)],
            )
            if i % 2 == 0:
                store.delete_relation_tuples(t("n:cycled#r@u"))
        # compaction fired at least once (40 loads, bound is 32 + the
        # loads that arrived after the merge)
        assert len(store._key_chunks) < 40
        # the cycled key's current owner resolves through the compacted
        # chunks to a LIVE row (i=38 deleted, i=39 re-added)
        src_id = store.vocab.lookup(("n", "cycled", "r"))
        dst_id = store.vocab.lookup(("u",))
        key = (src_id << 32) | dst_id
        assert store._alive_row_for_key(key) is not None
        tuples, _ = store.get_relation_tuples(
            RelationQuery(namespace="n", object="cycled")
        )
        assert len(tuples) == 1
        store.delete_relation_tuples(t("n:cycled#r@u"))
        tuples, _ = store.get_relation_tuples(
            RelationQuery(namespace="n", object="cycled")
        )
        assert tuples == []
