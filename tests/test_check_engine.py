"""Check-engine scenario tests — ports of the reference's engine suite
(reference internal/check/engine_test.go:45-581): direct/indirect inclusion,
exclusion, wrong object/relation, max-depth precedence, transitive rejection,
subject-id-next-to-subject-set, pagination behavior, wide graphs, circular
tuples."""

import pytest

from keto_tpu.engine.check import CheckEngine
from keto_tpu.namespace import MemoryNamespaceManager
from keto_tpu.relationtuple import (
    ManagerWrapper,
    RelationTuple,
    SubjectID,
    SubjectSet,
)
from keto_tpu.store import InMemoryTupleStore


def make_env(*namespaces, page_size=0):
    nsmgr = MemoryNamespaceManager()
    for n in namespaces:
        nsmgr.add(n)
    store = InMemoryTupleStore(namespace_manager=nsmgr)
    wrapped = ManagerWrapper(store, page_size=page_size)
    return store, wrapped, CheckEngine(wrapped)


def T(ns, obj, rel, subject):
    return RelationTuple(ns, obj, rel, subject)


class TestCheckEngine:
    def test_direct_inclusion(self):
        store, _, e = make_env("n")
        rel = T("n", "obj", "rel", SubjectID("user"))
        store.write_relation_tuples(rel)
        assert e.subject_is_allowed(rel)

    def test_direct_exclusion(self):
        store, _, e = make_env("n")
        store.write_relation_tuples(T("n", "obj", "rel", SubjectID("user-a")))
        assert not e.subject_is_allowed(T("n", "obj", "rel", SubjectID("user-b")))

    def test_wrong_object(self):
        store, _, e = make_env("n")
        store.write_relation_tuples(T("n", "object-a", "rel", SubjectID("user")))
        assert not e.subject_is_allowed(T("n", "object-b", "rel", SubjectID("user")))

    def test_wrong_relation(self):
        store, _, e = make_env("n")
        store.write_relation_tuples(T("n", "obj", "rel-a", SubjectID("user")))
        assert not e.subject_is_allowed(T("n", "obj", "rel-b", SubjectID("user")))

    def test_indirect_inclusion_level_1(self):
        # user is member of org; org members have access to obj
        store, _, e = make_env("n")
        store.write_relation_tuples(
            T("n", "org", "member", SubjectID("user")),
            T("n", "obj", "access", SubjectSet("n", "org", "member")),
        )
        assert e.subject_is_allowed(T("n", "obj", "access", SubjectID("user")))

    def test_indirect_inclusion_level_2(self):
        store, _, e = make_env("n")
        store.write_relation_tuples(
            T("n", "team", "member", SubjectID("user")),
            T("n", "org", "member", SubjectSet("n", "team", "member")),
            T("n", "obj", "access", SubjectSet("n", "org", "member")),
        )
        assert e.subject_is_allowed(T("n", "obj", "access", SubjectID("user")))

    def test_subject_set_as_requested_subject(self):
        # the requested subject may itself be a subject set
        store, _, e = make_env("n")
        store.write_relation_tuples(
            T("n", "obj", "access", SubjectSet("n", "org", "member")),
        )
        assert e.subject_is_allowed(
            T("n", "obj", "access", SubjectSet("n", "org", "member"))
        )

    def test_respects_max_depth(self):
        # reference engine_test.go:46-119: access <- owner <- admin <- user
        # requires depth 3; request and global max-depth interplay
        store, _, e = make_env("test")
        store.write_relation_tuples(
            T("test", "object", "admin", SubjectID("user")),
            T("test", "object", "owner", SubjectSet("test", "object", "admin")),
            T("test", "object", "access", SubjectSet("test", "object", "owner")),
        )
        req = T("test", "object", "access", SubjectID("user"))
        assert e.global_max_depth == 5
        # request max-depth takes precedence: 2 not enough, 3 enough
        assert not e.subject_is_allowed(req, 2)
        assert e.subject_is_allowed(req, 3)
        # global max-depth takes precedence when lesser
        e.global_max_depth = 2
        assert not e.subject_is_allowed(req, 3)
        # ...and when the request depth is 0
        e.global_max_depth = 3
        assert e.subject_is_allowed(req, 0)

    def test_rejects_transitive_relation(self):
        # (file) <-parent- (directory) <-access- [user]: no userset rewrite,
        # so access to the parent does not grant access to the file
        # (reference engine_test.go:348-387)
        store, _, e = make_env("")
        store.write_relation_tuples(
            T("", "file", "parent", SubjectSet("", "directory", "")),
            T("", "directory", "access", SubjectID("user")),
        )
        assert not e.subject_is_allowed(T("", "file", "access", SubjectID("user")))

    def test_subject_id_next_to_subject_set(self):
        # reference engine_test.go:388-440
        store, _, e = make_env("namesp")
        store.write_relation_tuples(
            T("namesp", "obj", "owner", SubjectID("u1")),
            T("namesp", "obj", "owner", SubjectSet("namesp", "org", "member")),
            T("namesp", "org", "member", SubjectID("u2")),
        )
        assert e.subject_is_allowed(T("namesp", "obj", "owner", SubjectID("u1")))
        assert e.subject_is_allowed(T("namesp", "obj", "owner", SubjectID("u2")))

    def test_paginates(self):
        # reference engine_test.go:441-485 asserts the engine walks pages via
        # the returned tokens; the ManagerWrapper spy records requested tokens
        store, wrapped, e = make_env("namesp", page_size=2)
        users = ["u1", "u2", "u3", "u4"]
        for u in users:
            store.write_relation_tuples(T("namesp", "obj", "access", SubjectID(u)))
        for i, u in enumerate(users):
            wrapped.requested_pages.clear()
            assert e.subject_is_allowed(T("namesp", "obj", "access", SubjectID(u)))
            # first page always requested with the empty token
            assert wrapped.requested_pages[0] == ""
            # u1/u2 live on page one; u3/u4 require a second page request
            assert len(wrapped.requested_pages) == (1 if i < 2 else 2)

    def test_wide_tuple_graph(self):
        # many sibling orgs; only one grants access (engine_test.go:487-528)
        store, _, e = make_env("n")
        width = 120  # spans multiple pages
        for i in range(width):
            store.write_relation_tuples(
                T("n", "obj", "access", SubjectSet("n", f"org-{i}", "member"))
            )
        store.write_relation_tuples(T("n", f"org-{width - 1}", "member", SubjectID("user")))
        assert e.subject_is_allowed(T("n", "obj", "access", SubjectID("user")))
        assert not e.subject_is_allowed(T("n", "obj", "access", SubjectID("nobody")))

    def test_circular_tuples(self):
        # A -connected-> B -connected-> C -connected-> A; a SubjectID that is
        # nowhere in the cycle must terminate and be denied
        # (reference engine_test.go:529-581)
        store, _, e = make_env("m")
        a, b, c = "Sendlinger Tor", "Odeonsplatz", "Central Station"
        store.write_relation_tuples(
            T("m", a, "connected", SubjectSet("m", b, "connected")),
            T("m", b, "connected", SubjectSet("m", c, "connected")),
            T("m", c, "connected", SubjectSet("m", a, "connected")),
        )
        assert not e.subject_is_allowed(T("m", a, "connected", SubjectID(c)))

    def test_unknown_namespace_is_denied(self):
        _, _, e = make_env("known")
        assert not e.subject_is_allowed(T("unknown", "o", "r", SubjectID("u")))

    def test_batch_check(self):
        store, _, e = make_env("n")
        store.write_relation_tuples(
            T("n", "org", "member", SubjectID("user")),
            T("n", "obj", "access", SubjectSet("n", "org", "member")),
        )
        reqs = [
            T("n", "obj", "access", SubjectID("user")),
            T("n", "obj", "access", SubjectID("other")),
            T("n", "org", "member", SubjectID("user")),
        ]
        assert e.batch_check(reqs) == [True, False, True]
