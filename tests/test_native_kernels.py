"""Native C kernels vs their numpy twins: bit-for-bit parity.

The native tier (keto_tpu/native) is pure performance — prefetch-pipelined
versions of the closure query and vocab probe. Any divergence from the numpy
paths is a correctness bug, so every kernel is tested against both its numpy
twin and the host oracle on random graphs, including rows whose fan-out
exceeds the numpy path's f0_max/l_max caps (where numpy falls back to the
oracle but C walks the true degrees)."""

import numpy as np
import pytest

from keto_tpu import native
from keto_tpu.engine import CheckEngine
from keto_tpu.engine.closure import ClosureCheckEngine
from keto_tpu.graph import SnapshotManager
from keto_tpu.relationtuple import RelationTuple
from keto_tpu.store import InMemoryTupleStore

from test_device_engines import random_store

pytestmark = pytest.mark.skipif(
    native.lib is None, reason="native kernels unavailable (no C compiler)"
)


def t(s: str) -> RelationTuple:
    return RelationTuple.from_string(s)


def _requests(rng, n_objects, n_users, k):
    reqs = []
    for _ in range(k):
        obj = f"o{rng.integers(n_objects)}"
        rel = f"r{rng.integers(3)}"
        if rng.random() < 0.3:
            sub = f"n:o{rng.integers(n_objects)}#r{rng.integers(3)}"
        else:
            sub = f"u{rng.integers(n_users)}"
        reqs.append(t(f"n:{obj}#{rel}@({sub})"))
    return reqs


class TestObjectHashes:
    def test_matches_python_hash(self):
        keys = [("ns", f"o{i}", "rel") for i in range(100)] + [
            (f"u{i}",) for i in range(100)
        ]
        h = native.object_hashes(keys)
        assert h.tolist() == [hash(k) for k in keys]

    def test_unhashable_raises(self):
        with pytest.raises(TypeError):
            native.object_hashes([["list", "unhashable"]])


class TestProbeParity:
    @pytest.mark.parametrize("seed", range(3))
    def test_lookup_bulk_native_vs_numpy(self, seed, monkeypatch):
        from keto_tpu.graph.vocab import NodeVocab

        rng = np.random.default_rng(seed)
        vocab = NodeVocab()
        keys = [("n", f"o{i}", f"r{i % 3}") for i in range(2000)] + [
            (f"u{i}",) for i in range(2000)
        ]
        vocab.intern_bulk(keys)
        probe = [keys[i] for i in rng.integers(len(keys), size=500)]
        probe += [("n", "missing", "x"), ("nouser",)] * 10
        got_native = vocab.lookup_bulk(probe)
        monkeypatch.setattr(native, "lib", None)
        got_numpy = vocab.lookup_bulk(probe)
        np.testing.assert_array_equal(got_native, got_numpy)
        # and both agree with the exact dict
        exact = [
            v if (v := vocab.lookup(k)) is not None else -1 for k in probe
        ]
        assert got_native.tolist() == exact


class TestRequestHashes:
    def test_tuple_hash_parity(self):
        assert native.tuple_hash_ok
        for tup in [("a", "b", "c"), ("x",), ("", "", ""), ("u" * 99,)]:
            assert native.lib.tuple_hash_check(tup) == hash(tup)

    def test_hashes_and_flags(self):
        from keto_tpu.relationtuple import SubjectID, SubjectSet

        reqs = [
            t("n:o1#r@alice"),
            t("n:o2#r@(m:g#member)"),
            t(":#@()"),  # empty-string fields are legal key material
        ]
        hs, ht, is_id = native.request_hashes(reqs, SubjectID)
        for i, r in enumerate(reqs):
            assert hs[i] == hash((r.namespace, r.object, r.relation))
            s = r.subject
            want = (
                hash((s.id,))
                if isinstance(s, SubjectID)
                else hash((s.namespace, s.object, s.relation))
            )
            assert ht[i] == want
            assert is_id[i] == isinstance(s, SubjectID)

    def test_lookup_hashes_matches_lookup_bulk(self):
        from keto_tpu.graph.vocab import NodeVocab

        vocab = NodeVocab()
        keys = [("n", f"o{i}", "r") for i in range(500)] + [
            (f"u{i}",) for i in range(500)
        ]
        vocab.intern_bulk(keys)
        probe = keys[::3] + [("n", "nope", "r"), ("ghost",)]
        h = np.fromiter((hash(k) for k in probe), np.int64, count=len(probe))
        got = vocab.lookup_hashes(h, lambda i: probe[i])
        want = vocab.lookup_bulk(probe)
        np.testing.assert_array_equal(got, want)

    def test_lookup_hashes_collision_fallback(self):
        """Keys routed to the exact dict when their hash collides inside
        the vocab must still resolve through key_fn."""
        from keto_tpu.graph.vocab import NodeVocab

        vocab = NodeVocab()
        keys = [("n", f"o{i}", "r") for i in range(64)]
        vocab.intern_bulk(keys)
        vocab._extend_hash_index()
        # force a recorded collision for one stored hash
        mask, slots, slot_ids, collisions, upto = vocab._h_table
        victim = keys[7]
        collisions.add(hash(victim))
        vocab._h_table = (mask, slots, slot_ids, collisions, upto)
        h = np.array([hash(victim)], np.int64)
        got = vocab.lookup_hashes(h, lambda i: victim)
        assert got[0] == vocab.lookup(victim)
        # a DIFFERENT key with that same hash value resolves to unknown
        got_missing = vocab.lookup_hashes(h, lambda i: ("not", "a", "key"))
        assert got_missing[0] == -1


class TestClosureCheckParity:
    @pytest.mark.parametrize("seed", range(4))
    def test_native_vs_numpy_vs_oracle(self, seed, monkeypatch):
        rng = np.random.default_rng(seed)
        store = random_store(rng, n_objects=15, n_users=10, n_edges=150)
        reqs = _requests(rng, 15, 10, 128)
        for depth in (1, 2, 3, 5):
            oracle = CheckEngine(store, max_depth=depth)
            eng = ClosureCheckEngine(
                SnapshotManager(store), max_depth=depth
            )
            got_native = eng.batch_check(reqs)
            monkeypatch.setattr(native, "lib", None)
            eng2 = ClosureCheckEngine(
                SnapshotManager(store), max_depth=depth
            )
            got_numpy = eng2.batch_check(reqs)
            monkeypatch.undo()
            expect = oracle.batch_check(reqs)
            assert got_native == expect
            assert got_numpy == expect

    def test_wide_fanout_exceeding_numpy_caps(self):
        """Rows wider than f0_max/l_max: numpy falls back to the oracle,
        C walks true degrees — both must match the oracle."""
        store = InMemoryTupleStore()
        tuples = []
        # start with 70 set successors (> f0_max=32)
        for i in range(70):
            tuples.append(t(f"n:doc#view@(n:g{i}#m)"))
            tuples.append(t(f"n:g{i}#m@(n:h{i}#m)"))
        # target with 50 interior in-neighbors (> l_max=32)
        for i in range(50):
            tuples.append(t(f"n:h{i}#m@alice"))
        store.write_relation_tuples(*tuples)
        oracle = CheckEngine(store, max_depth=5)
        eng = ClosureCheckEngine(SnapshotManager(store), max_depth=5)
        reqs = [
            t("n:doc#view@alice"),
            t("n:doc#view@bob"),
            t("n:doc#view@(n:g3#m)"),
            t("n:doc#view@(n:h9#m)"),
        ]
        assert eng.batch_check(reqs) == oracle.batch_check(reqs)
        # per-request depths through the same path
        assert eng.batch_check(reqs, depths=[1, 2, 3, 4]) == oracle.batch_check(
            reqs, depths=[1, 2, 3, 4]
        )

    def test_mixed_depths_and_direct_edges(self):
        store = InMemoryTupleStore()
        store.write_relation_tuples(
            t("n:a#r@alice"),
            t("n:a#r@(n:b#r)"),
            t("n:b#r@(n:c#r)"),
            t("n:c#r@bob"),
        )
        oracle = CheckEngine(store, max_depth=8)
        eng = ClosureCheckEngine(SnapshotManager(store), max_depth=8)
        reqs = [
            t("n:a#r@alice"),  # direct, depth 1
            t("n:a#r@bob"),  # 3 hops
            t("n:a#r@(n:c#r)"),  # set target, 2 hops
            t("n:a#r@(n:a#r)"),  # self
            t("n:zzz#r@alice"),  # unknown start
        ]
        for depths in (None, [1, 1, 1, 1, 1], [1, 3, 2, 1, 5], [2, 2, 2, 2, 2]):
            assert eng.batch_check(reqs, depths=depths) == oracle.batch_check(
                reqs, depths=depths
            )


class TestGatherMin:
    def test_matches_numpy(self):
        rng = np.random.default_rng(0)
        d = rng.integers(0, 256, size=(64, 64), dtype=np.uint8)
        rows = rng.integers(0, 64, size=(40, 5)).astype(np.int32)
        cols = rng.integers(0, 64, size=(40, 3)).astype(np.int32)
        got = native.gather_min_u8(d, rows, cols)
        want = d[rows[:, :, None], cols[:, None, :]].min(axis=(1, 2))
        np.testing.assert_array_equal(got, want)
