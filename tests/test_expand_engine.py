"""Expand-engine tests — ports of reference internal/expand/engine_test.go:
leaf for subject ids, one/two-level expansion, max-depth degradation to leaf,
pagination, subject-set leaves, circular tuples."""

from keto_tpu.engine.expand import ExpandEngine
from keto_tpu.engine.tree import NodeType, Tree
from keto_tpu.namespace import MemoryNamespaceManager
from keto_tpu.relationtuple import RelationTuple, SubjectID, SubjectSet
from keto_tpu.store import InMemoryTupleStore


def make_env(*namespaces):
    nsmgr = MemoryNamespaceManager()
    for n in namespaces:
        nsmgr.add(n)
    store = InMemoryTupleStore(namespace_manager=nsmgr)
    return store, ExpandEngine(store)


def T(ns, obj, rel, subject):
    return RelationTuple(ns, obj, rel, subject)


def subjects_of(tree):
    return {str(c.subject) for c in tree.children}


class TestExpandEngine:
    def test_subject_id_is_leaf(self):
        _, e = make_env("n")
        tree = e.build_tree(SubjectID("user"), 100)
        assert tree == Tree(type=NodeType.LEAF, subject=SubjectID("user"))

    def test_expands_one_level(self):
        store, e = make_env("n")
        root = SubjectSet("n", "obj", "access")
        store.write_relation_tuples(
            T("n", "obj", "access", SubjectID("u1")),
            T("n", "obj", "access", SubjectID("u2")),
        )
        tree = e.build_tree(root, 100)
        assert tree.type == NodeType.UNION
        assert tree.subject == root
        assert subjects_of(tree) == {"u1", "u2"}
        assert all(c.type == NodeType.LEAF for c in tree.children)

    def test_expands_two_levels(self):
        store, e = make_env("n")
        root = SubjectSet("n", "z", "access")
        store.write_relation_tuples(
            T("n", "z", "access", SubjectSet("n", "x", "member")),
            T("n", "x", "member", SubjectID("u1")),
            T("n", "x", "member", SubjectID("u2")),
        )
        tree = e.build_tree(root, 100)
        assert tree.type == NodeType.UNION
        (child,) = tree.children
        assert child.type == NodeType.UNION
        assert child.subject == SubjectSet("n", "x", "member")
        assert subjects_of(child) == {"u1", "u2"}

    def test_respects_max_depth_degrades_to_leaf(self):
        # reference expand engine_test.go:179-236: at rest depth 1 a subject
        # set with tuples becomes a leaf instead of expanding
        store, e = make_env("n")
        root = SubjectSet("n", "z", "access")
        store.write_relation_tuples(
            T("n", "z", "access", SubjectSet("n", "x", "member")),
            T("n", "x", "member", SubjectID("u1")),
        )
        tree = e.build_tree(root, 1)
        assert tree == Tree(type=NodeType.LEAF, subject=root)

        tree = e.build_tree(root, 2)
        (child,) = tree.children
        assert child == Tree(type=NodeType.LEAF, subject=SubjectSet("n", "x", "member"))

    def test_paginates_across_pages(self):
        store, e = make_env("n")
        root = SubjectSet("n", "obj", "access")
        users = [f"u{i:03d}" for i in range(250)]  # > 2 default pages
        store.write_relation_tuples(
            *[T("n", "obj", "access", SubjectID(u)) for u in users]
        )
        tree = e.build_tree(root, 100)
        assert subjects_of(tree) == set(users)

    def test_subject_set_without_tuples_becomes_leaf_child(self):
        store, e = make_env("n")
        root = SubjectSet("n", "obj", "access")
        store.write_relation_tuples(
            T("n", "obj", "access", SubjectSet("n", "empty", "member")),
        )
        tree = e.build_tree(root, 100)
        # reference returns nil for an empty subject set (engine.go:67-69) but
        # the parent substitutes a Leaf for the nil child (engine.go:80-86)
        assert tree.type == NodeType.UNION
        (child,) = tree.children
        assert child == Tree(
            type=NodeType.LEAF, subject=SubjectSet("n", "empty", "member")
        )

    def test_circular_tuples_terminate(self):
        store, e = make_env("m")
        a, b = "A", "B"
        store.write_relation_tuples(
            T("m", a, "connected", SubjectSet("m", b, "connected")),
            T("m", b, "connected", SubjectSet("m", a, "connected")),
        )
        tree = e.build_tree(SubjectSet("m", a, "connected"), 100)
        # A expands to B; B's re-expansion of A is suppressed by the visited
        # set, degrading to a Leaf child (engine.go:80-86) — never dropped
        assert tree.type == NodeType.UNION
        (child,) = tree.children
        assert child.subject == SubjectSet("m", b, "connected")
        assert child.type == NodeType.UNION
        (grandchild,) = child.children
        assert grandchild == Tree(
            type=NodeType.LEAF, subject=SubjectSet("m", a, "connected")
        )

    def test_unknown_namespace_returns_none(self):
        _, e = make_env("known")
        assert e.build_tree(SubjectSet("unknown", "o", "r"), 5) is None

    def test_tree_json_roundtrip(self):
        store, e = make_env("n")
        root = SubjectSet("n", "z", "access")
        store.write_relation_tuples(
            T("n", "z", "access", SubjectSet("n", "x", "member")),
            T("n", "x", "member", SubjectID("u1")),
        )
        tree = e.build_tree(root, 100)
        assert Tree.from_dict(tree.to_dict()) == tree

    def test_tree_pretty_print(self):
        store, e = make_env("n")
        root = SubjectSet("n", "obj", "access")
        store.write_relation_tuples(T("n", "obj", "access", SubjectID("u1")))
        s = str(e.build_tree(root, 100))
        assert "∪ n:obj#access" in s
        assert "u1" in s
