"""Sharded SERVING tier correctness on a multi-device mesh.

The serving wrapper (parallel/serving.ShardedServingEngine) is the path
live check traffic takes when engine.sharding.enabled: CheckBatcher ->
breaker -> encode/launch/decode over the edge-partitioned mesh. These
tests pin parity with the host oracle, the overflow/escalation contract,
incremental re-shard across snapshot rebuilds, mesh-shape validation,
and the breaker interaction under injected launch faults.

Runs only when >= 8 devices are visible (the 8-device virtual CPU mesh);
under the single-chip axon backend these skip and the subprocess wrapper
(test_sharded_subprocess.py) re-runs them with the right interpreter env.
The HBM clamp tests at the bottom need no mesh and always run.
"""

import jax
import numpy as np
import pytest

from keto_tpu.engine import CheckEngine
from keto_tpu.engine.batcher import CheckBatcher
from keto_tpu.engine.fallback import DeviceFallbackEngine
from keto_tpu.engine.hbm import HbmAdmission
from keto_tpu.faults import FAULTS
from keto_tpu.graph import SnapshotManager
from keto_tpu.parallel import make_mesh
from keto_tpu.parallel.serving import ShardedServingEngine
from keto_tpu.relationtuple import RelationTuple
from keto_tpu.store import InMemoryTupleStore

needs_mesh = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 devices (virtual CPU mesh)"
)


@pytest.fixture(autouse=True)
def _clean_faults():
    FAULTS.reset()
    yield
    FAULTS.reset()


def t(s: str) -> RelationTuple:
    return RelationTuple.from_string(s)


# unicode vocab: the serving tier encodes/decodes ids against the
# snapshot vocab; multi-byte keys must survive the round trip
_UNI_OBJS = ["документ", "予約-α", "ficha-ñ", "plain"]
_UNI_USERS = ["алиса", "ユーザー1", "böb", "mallory"]


def fuzz_store(rng, n_edges=300):
    store = InMemoryTupleStore()
    tuples = set()
    for _ in range(n_edges):
        obj = f"o{rng.integers(20)}"
        rel = f"r{rng.integers(3)}"
        if rng.random() < 0.45:
            sub = f"n:o{rng.integers(20)}#r{rng.integers(3)}"
        else:
            sub = f"u{rng.integers(12)}"
        tuples.add(f"n:{obj}#{rel}@({sub})")
    # unicode spine, including a cycle through the multi-byte nodes
    for i, (o, u) in enumerate(zip(_UNI_OBJS, _UNI_USERS)):
        tuples.add(f"n:{o}#view@({u})")
        tuples.add(f"n:o{i}#r0@(n:{o}#view)")
    tuples.add(f"n:{_UNI_OBJS[0]}#view@(n:{_UNI_OBJS[1]}#view)")
    tuples.add(f"n:{_UNI_OBJS[1]}#view@(n:{_UNI_OBJS[0]}#view)")
    store.write_relation_tuples(*(t(s) for s in tuples))
    return store


def fuzz_requests(rng, n=96):
    reqs = []
    for _ in range(n):
        roll = rng.random()
        if roll < 0.15:
            obj = _UNI_OBJS[rng.integers(len(_UNI_OBJS))]
            rel = "view"
        else:
            obj = f"o{rng.integers(20)}"
            rel = f"r{rng.integers(3)}"
        if roll < 0.15:
            sub = _UNI_USERS[rng.integers(len(_UNI_USERS))]
        elif roll < 0.4:
            sub = f"(n:o{rng.integers(20)}#r{rng.integers(3)})"
        else:
            sub = f"u{rng.integers(12)}"
        reqs.append(t(f"n:{obj}#{rel}@{sub}"))
    return reqs


def make_batcher(engine, store, **kw):
    breaker = DeviceFallbackEngine(
        engine,
        fallback_factory=lambda: CheckEngine(store, max_depth=5),
        failure_threshold=3,
        cooldown_s=0.1,
    )
    return CheckBatcher(breaker, max_batch=256, window_s=0.0, **kw)


def encode(snap, reqs):
    start = np.array(
        [snap.node_for_set(r.namespace, r.object, r.relation) for r in reqs],
        dtype=np.int64,
    )
    target = np.array(
        [snap.node_for_subject(r.subject) for r in reqs], dtype=np.int64
    )
    return start, target


@needs_mesh
@pytest.mark.parametrize("mesh_shape", [(1, 8), (2, 4), (4, 2)])
def test_serving_parity_fuzz(mesh_shape):
    """batch_check parity with the host oracle over a fuzzed store with
    unicode vocab and cycles, across mesh shapes and depth vectors."""
    rng = np.random.default_rng(11)
    store = fuzz_store(rng)
    mgr = SnapshotManager(store)
    data, edge = mesh_shape
    eng = ShardedServingEngine(
        mgr, mesh=make_mesh(data=data, edge=edge), max_depth=5
    )
    host = CheckEngine(store, max_depth=5)
    reqs = fuzz_requests(rng)
    for depths in (None, [1 + (i % 5) for i in range(len(reqs))]):
        got = eng.batch_check(reqs, depths=depths)
        want = host.batch_check(reqs, depths=depths)
        assert got == want, mesh_shape


@needs_mesh
def test_serving_through_check_batcher_encoded():
    """The production route: CheckBatcher.check_batch_encoded over the
    breaker-wrapped serving engine, byte-identical to the host oracle."""
    rng = np.random.default_rng(12)
    store = fuzz_store(rng)
    mgr = SnapshotManager(store)
    eng = ShardedServingEngine(mgr, mesh=make_mesh(data=2, edge=4), max_depth=5)
    host = CheckEngine(store, max_depth=5)
    batcher = make_batcher(eng, store)
    try:
        reqs = fuzz_requests(rng, n=64)
        start, target = encode(mgr.snapshot(), reqs)
        got = batcher.check_batch_encoded(start, target)
        assert got == host.batch_check(reqs)
        # string path too (same batcher seam the gRPC front uses)
        assert batcher.check_batch(reqs) == host.batch_check(reqs)
    finally:
        batcher.close()


@needs_mesh
def test_serving_overflow_escalates_to_host_oracle():
    """Rows wider than even the escalated gather widths reach the host
    oracle and stay exact; the escalation counters move accordingly."""
    store = InMemoryTupleStore()
    tuples = [t("n:doc#view@(n:g0#m)")]
    for i in range(120):  # alice in 120 groups: L row way past widths
        tuples.append(t(f"n:g{i}#m@alice"))
        tuples.append(t(f"n:top#r@(n:g{i}#m)"))  # make every g interior
    store.write_relation_tuples(*tuples)
    mgr = SnapshotManager(store)
    reqs = [
        t("n:doc#view@alice"),
        t("n:top#r@alice"),
        t("n:doc#view@mallory"),
    ]
    # wide escalated widths: stays on device
    eng = ShardedServingEngine(
        mgr, mesh=make_mesh(data=1, edge=8), max_depth=5
    )
    assert eng.batch_check(reqs) == [True, True, False]
    assert eng.overflow_stats["escalated"] > 0
    assert eng.overflow_stats["host_fallback"] == 0
    # narrow escalated widths: host oracle answers, exactly, and the
    # budget-breach accounting sees the rate
    eng2 = ShardedServingEngine(
        mgr,
        mesh=make_mesh(data=1, edge=8),
        max_depth=5,
        f0_max_escalated=64,
        l_max_escalated=64,
        escalation_budget=0.01,
    )
    assert eng2.batch_check(reqs) == [True, True, False]
    assert eng2.overflow_stats["host_fallback"] > 0
    assert eng2.n_budget_breaches > 0


@needs_mesh
def test_serving_snapshot_rebuild_reuses_residency():
    """An append-only write must re-shard incrementally (dirty rows +
    affected stripes only), not rebuild the closure from scratch — and
    stay exact afterwards."""
    rng = np.random.default_rng(13)
    store = fuzz_store(rng)
    mgr = SnapshotManager(store)
    eng = ShardedServingEngine(
        mgr, mesh=make_mesh(data=2, edge=4), max_depth=5
    )
    reqs = fuzz_requests(rng, n=48)
    eng.batch_check(reqs)
    assert eng.n_full_reshards == 1
    assert eng.n_incremental_reshards == 0
    # append-only delta touching interior rows (set -> set edge)
    store.write_relation_tuples(
        t("n:o1#r0@(n:o2#r1)"), t("n:o2#r1@(n:o3#r2)"), t("n:o3#r2@zoe")
    )
    host = CheckEngine(store, max_depth=5)
    got = eng.batch_check(reqs + [t("n:o1#r0@zoe")])
    assert got == host.batch_check(reqs + [t("n:o1#r0@zoe")])
    assert eng.n_full_reshards == 1
    assert eng.n_incremental_reshards == 1
    assert eng.last_reshard["kind"] == "incremental"
    assert eng.last_reshard["dirty_rows"] >= 1


@needs_mesh
def test_mesh_shape_validation_errors():
    with pytest.raises(ValueError):
        make_mesh(jax.devices()[:8], data=3, edge=3)  # 9 != 8
    with pytest.raises(ValueError):
        make_mesh(jax.devices()[:8], data=16, edge=1)


@needs_mesh
def test_breaker_answers_via_oracle_on_launch_fault():
    """KETO_FAULTS site shard.launch_fail: the breaker catches the
    injected launch failure and the host oracle answers — exactly —
    then the device path resumes once the fault disarms."""
    rng = np.random.default_rng(14)
    store = fuzz_store(rng)
    mgr = SnapshotManager(store)
    eng = ShardedServingEngine(
        mgr, mesh=make_mesh(data=1, edge=8), max_depth=5
    )
    host = CheckEngine(store, max_depth=5)
    batcher = make_batcher(eng, store)
    try:
        reqs = fuzz_requests(rng, n=32)
        start, target = encode(mgr.snapshot(), reqs)
        want = host.batch_check(reqs)
        FAULTS.arm("shard.launch_fail", times=1)
        assert batcher.check_batch_encoded(start, target) == want
        assert FAULTS.fired("shard.launch_fail") == 1
        # fault disarmed: the device path serves again and still agrees
        assert batcher.check_batch_encoded(start, target) == want
    finally:
        batcher.close()


class _FakeDevstats:
    def __init__(self, limit, peak=0, n=2):
        self.limit = limit
        self.peak = peak
        self.n = n

    def sample_devices(self):
        return [
            {
                "memory_stats": {
                    "bytes_in_use": 0,
                    "bytes_limit": self.limit,
                    "peak_bytes_in_use": self.peak,
                }
            }
            for _ in range(self.n)
        ]


class TestPerShardHbmClamp:
    """No mesh needed: the admission math over pinned shard residency."""

    def test_clamp_respects_fullest_shard(self):
        hbm = HbmAdmission(
            budget_frac=1.0,
            bytes_per_row=100,
            devstats=_FakeDevstats(limit=1_000_000),
        )
        assert hbm.clamp_rows(8192) == 8192
        # pin 920k on the fullest shard: 80k headroom / 100 B = 800 rows
        hbm.set_shard_residency({0: 500_000.0, 1: 920_000.0})
        assert hbm.clamp_rows(8192) == 800
        assert hbm.snapshot()["resident_floor_bytes"] == 920_000.0
        # rebalance: residency drops, clamp relaxes
        hbm.set_shard_residency({0: 500_000.0, 1: 500_000.0})
        assert hbm.clamp_rows(8192) == 5000

    def test_clamp_floor_under_full_residency(self):
        hbm = HbmAdmission(
            budget_frac=1.0,
            bytes_per_row=100,
            devstats=_FakeDevstats(limit=1_000_000),
        )
        hbm.set_shard_residency({0: 2_000_000.0})  # over budget
        # never clamps below the minimum viable batch
        assert hbm.clamp_rows(8192) >= 1

    def test_shard_peak_model_learns(self):
        stats = _FakeDevstats(limit=1_000_000, peak=0, n=2)
        hbm = HbmAdmission(bytes_per_row=100, devstats=stats)
        tok = hbm.reserve(128, 1)
        stats.peak = 48_000
        hbm.release(tok)
        assert hbm.modeled_shard_bytes(128, 1, 0) == pytest.approx(48_000)
        assert hbm.modeled_shard_bytes(128, 1, 1) == pytest.approx(48_000)
        assert hbm.snapshot()["modeled_shard_shapes"] >= 1
