"""Read-replica worker pool (driver/replicas.py): fork-shared residency,
SO_REUSEPORT port sharing, and delta-stream freshness across processes —
the framework's answer to the reference's stateless-replica scale-out row
(SURVEY §2.10; VERDICT r3 #4)."""

import asyncio
import threading
import time

import httpx
import pytest

from keto_tpu.driver import Config, Registry


@pytest.fixture()
def pool_server():
    cfg = Config(
        values={
            "namespaces": [{"id": 1, "name": "n"}],
            "log": {"level": "error"},
            "serve": {
                "read": {"port": 0, "host": "127.0.0.1", "workers": 3},
                "write": {"port": 0, "host": "127.0.0.1"},
            },
        }
    )
    reg = Registry(cfg)
    loop = asyncio.new_event_loop()
    threading.Thread(target=loop.run_forever, daemon=True).start()
    rp, wp = asyncio.run_coroutine_threadsafe(
        reg.start_all(), loop
    ).result(timeout=120)
    yield reg, rp, wp
    asyncio.run_coroutine_threadsafe(reg.stop_all(), loop).result(
        timeout=30
    )
    loop.call_soon_threadsafe(loop.stop)


def _converges(rp, params, want_status, tries=24, timeout=120.0):
    """Fresh connection per probe: SO_REUSEPORT spreads them over the
    replicas, so `tries` consecutive agreements cover the whole pool."""
    deadline = time.time() + timeout
    streak = 0
    while streak < tries and time.time() < deadline:
        r = httpx.get(f"http://127.0.0.1:{rp}/check", params=params)
        if r.status_code == want_status:
            streak += 1
        else:
            streak = 0
            time.sleep(0.05)
    return streak >= tries


class TestReplicaPool:
    def test_forked_and_serving(self, pool_server):
        reg, rp, wp = pool_server
        assert reg._replica_pool is not None
        assert len(reg._replica_pool._children) == 2  # parent is replica 0
        # engine forced into host query mode (children must not touch jax)
        assert reg.check_engine().host_queries()

    def test_write_delete_propagate_to_every_replica(self, pool_server):
        reg, rp, wp = pool_server
        tup = {
            "namespace": "n", "object": "doc", "relation": "view",
            "subject_id": "alice",
        }
        r = httpx.put(f"http://127.0.0.1:{wp}/relation-tuples", json=tup)
        assert r.status_code == 201
        assert _converges(rp, tup, 200)
        r = httpx.request(
            "DELETE",
            f"http://127.0.0.1:{wp}/relation-tuples",
            params=tup,
        )
        assert r.status_code == 204
        assert _converges(rp, tup, 403)

    def test_indirect_path_through_replicas(self, pool_server):
        reg, rp, wp = pool_server
        for body in (
            {"namespace": "n", "object": "g", "relation": "m",
             "subject_id": "bob"},
            {"namespace": "n", "object": "doc2", "relation": "view",
             "subject_set": {"namespace": "n", "object": "g",
                              "relation": "m"}},
        ):
            assert (
                httpx.put(
                    f"http://127.0.0.1:{wp}/relation-tuples", json=body
                ).status_code
                == 201
            )
        assert _converges(
            rp,
            {"namespace": "n", "object": "doc2", "relation": "view",
             "subject_id": "bob"},
            200,
        )


class TestSpawnWorkers:
    """SQL-backed scale-out spawns fresh worker processes (the reference's
    stateless-replica model) instead of forking — immune to
    fork-after-threads by construction (VERDICT r4 weak #4)."""

    def test_sql_store_workers_spawn_and_serve(self, tmp_path):
        # a deliberately-live extra thread: spawning must not care
        stop = threading.Event()
        ticker = threading.Thread(
            target=stop.wait, name="metrics-ticker", daemon=True
        )
        ticker.start()
        try:
            cfg = Config(
                values={
                    "namespaces": [{"id": 1, "name": "n"}],
                    "log": {"level": "error"},
                    "dsn": f"sqlite://{tmp_path}/pool.db",
                    "serve": {
                        "read": {
                            "port": 0, "host": "127.0.0.1", "workers": 3,
                        },
                        "write": {"port": 0, "host": "127.0.0.1"},
                    },
                }
            )
            reg = Registry(cfg)
            loop = asyncio.new_event_loop()
            threading.Thread(target=loop.run_forever, daemon=True).start()
            rp, wp = asyncio.run_coroutine_threadsafe(
                reg.start_all(), loop
            ).result(timeout=180)
            try:
                from keto_tpu.driver.spawn_workers import SpawnWorkerPool

                pool = reg._replica_pool
                assert isinstance(pool, SpawnWorkerPool)
                assert pool.wait_ready(60)
                assert pool.alive() == 3
                tup = {
                    "namespace": "n", "object": "doc", "relation": "view",
                    "subject_id": "alice",
                }
                r = httpx.put(
                    f"http://127.0.0.1:{wp}/relation-tuples", json=tup
                )
                assert r.status_code == 201
                assert _converges(rp, tup, 200)
                # delete propagates through the shared database
                r = httpx.request(
                    "DELETE",
                    f"http://127.0.0.1:{wp}/relation-tuples",
                    params=tup,
                )
                assert r.status_code == 204
                assert _converges(rp, tup, 403)
            finally:
                asyncio.run_coroutine_threadsafe(
                    reg.stop_all(), loop
                ).result(timeout=30)
                loop.call_soon_threadsafe(loop.stop)
        finally:
            stop.set()

    def test_fork_inventory_rejects_unexpected_threads(self):
        from keto_tpu.driver.replicas import ReplicaPool

        stop = threading.Event()
        rogue = threading.Thread(
            target=stop.wait, name="rogue-worker", daemon=True
        )
        rogue.start()
        try:
            pool = ReplicaPool.__new__(ReplicaPool)
            with pytest.raises(RuntimeError, match="rogue-worker"):
                pool._enforce_fork_inventory()
        finally:
            stop.set()
