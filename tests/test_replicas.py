"""Read-replica worker pool (driver/replicas.py): fork-shared residency,
SO_REUSEPORT port sharing, and delta-stream freshness across processes —
the framework's answer to the reference's stateless-replica scale-out row
(SURVEY §2.10; VERDICT r3 #4)."""

import asyncio
import threading
import time

import httpx
import pytest

from keto_tpu.driver import Config, Registry


@pytest.fixture()
def pool_server():
    cfg = Config(
        values={
            "namespaces": [{"id": 1, "name": "n"}],
            "log": {"level": "error"},
            "serve": {
                "read": {"port": 0, "host": "127.0.0.1", "workers": 3},
                "write": {"port": 0, "host": "127.0.0.1"},
            },
        }
    )
    reg = Registry(cfg)
    loop = asyncio.new_event_loop()
    threading.Thread(target=loop.run_forever, daemon=True).start()
    rp, wp = asyncio.run_coroutine_threadsafe(
        reg.start_all(), loop
    ).result(timeout=120)
    yield reg, rp, wp
    asyncio.run_coroutine_threadsafe(reg.stop_all(), loop).result(
        timeout=30
    )
    loop.call_soon_threadsafe(loop.stop)


def _converges(rp, params, want_status, tries=24, timeout=120.0):
    """Fresh connection per probe: SO_REUSEPORT spreads them over the
    replicas, so `tries` consecutive agreements cover the whole pool."""
    deadline = time.time() + timeout
    streak = 0
    while streak < tries and time.time() < deadline:
        r = httpx.get(f"http://127.0.0.1:{rp}/check", params=params)
        if r.status_code == want_status:
            streak += 1
        else:
            streak = 0
            time.sleep(0.05)
    return streak >= tries


class TestReplicaPool:
    def test_forked_and_serving(self, pool_server):
        reg, rp, wp = pool_server
        assert reg._replica_pool is not None
        assert len(reg._replica_pool._children) == 2  # parent is replica 0
        # engine forced into host query mode (children must not touch jax)
        assert reg.check_engine().host_queries()

    def test_write_delete_propagate_to_every_replica(self, pool_server):
        reg, rp, wp = pool_server
        tup = {
            "namespace": "n", "object": "doc", "relation": "view",
            "subject_id": "alice",
        }
        r = httpx.put(f"http://127.0.0.1:{wp}/relation-tuples", json=tup)
        assert r.status_code == 201
        assert _converges(rp, tup, 200)
        r = httpx.request(
            "DELETE",
            f"http://127.0.0.1:{wp}/relation-tuples",
            params=tup,
        )
        assert r.status_code == 204
        assert _converges(rp, tup, 403)

    def test_indirect_path_through_replicas(self, pool_server):
        reg, rp, wp = pool_server
        for body in (
            {"namespace": "n", "object": "g", "relation": "m",
             "subject_id": "bob"},
            {"namespace": "n", "object": "doc2", "relation": "view",
             "subject_set": {"namespace": "n", "object": "g",
                              "relation": "m"}},
        ):
            assert (
                httpx.put(
                    f"http://127.0.0.1:{wp}/relation-tuples", json=body
                ).status_code
                == 201
            )
        assert _converges(
            rp,
            {"namespace": "n", "object": "doc2", "relation": "view",
             "subject_id": "bob"},
            200,
        )


class TestSpawnWorkers:
    """SQL-backed scale-out spawns fresh worker processes (the reference's
    stateless-replica model) instead of forking — immune to
    fork-after-threads by construction (VERDICT r4 weak #4)."""

    def test_sql_store_workers_spawn_and_serve(self, tmp_path):
        # a deliberately-live extra thread: spawning must not care
        stop = threading.Event()
        ticker = threading.Thread(
            target=stop.wait, name="metrics-ticker", daemon=True
        )
        ticker.start()
        try:
            cfg = Config(
                values={
                    "namespaces": [{"id": 1, "name": "n"}],
                    "log": {"level": "error"},
                    "dsn": f"sqlite://{tmp_path}/pool.db",
                    "serve": {
                        "read": {
                            "port": 0, "host": "127.0.0.1", "workers": 3,
                        },
                        "write": {"port": 0, "host": "127.0.0.1"},
                    },
                }
            )
            reg = Registry(cfg)
            loop = asyncio.new_event_loop()
            threading.Thread(target=loop.run_forever, daemon=True).start()
            rp, wp = asyncio.run_coroutine_threadsafe(
                reg.start_all(), loop
            ).result(timeout=180)
            try:
                from keto_tpu.driver.spawn_workers import SpawnWorkerPool

                pool = reg._replica_pool
                assert isinstance(pool, SpawnWorkerPool)
                assert pool.wait_ready(60)
                assert pool.alive() == 3
                tup = {
                    "namespace": "n", "object": "doc", "relation": "view",
                    "subject_id": "alice",
                }
                r = httpx.put(
                    f"http://127.0.0.1:{wp}/relation-tuples", json=tup
                )
                assert r.status_code == 201
                assert _converges(rp, tup, 200)
                # delete propagates through the shared database
                r = httpx.request(
                    "DELETE",
                    f"http://127.0.0.1:{wp}/relation-tuples",
                    params=tup,
                )
                assert r.status_code == 204
                assert _converges(rp, tup, 403)
            finally:
                asyncio.run_coroutine_threadsafe(
                    reg.stop_all(), loop
                ).result(timeout=30)
                loop.call_soon_threadsafe(loop.stop)
        finally:
            stop.set()

    def test_fork_inventory_rejects_unexpected_threads(self):
        from keto_tpu.driver.replicas import ReplicaPool

        stop = threading.Event()
        rogue = threading.Thread(
            target=stop.wait, name="rogue-worker", daemon=True
        )
        rogue.start()
        try:
            pool = ReplicaPool.__new__(ReplicaPool)
            with pytest.raises(RuntimeError, match="rogue-worker"):
                pool._enforce_fork_inventory()
        finally:
            stop.set()


class TestHedgedReads:
    """Client-side hedged single-check reads (client/hedge.py): the
    ``replica.slow`` fault site stands in for the one briefly-slow worker
    an SO_REUSEPORT reissue would dodge — the hedge must mask its stall,
    fire at most once, and discard the loser's answer."""

    @pytest.fixture(autouse=True)
    def _clean_faults(self):
        from keto_tpu.faults import FAULTS

        FAULTS.reset()
        yield
        FAULTS.reset()

    def _counters(self):
        from keto_tpu.telemetry import MetricsRegistry
        from keto_tpu.telemetry.metrics import hedge_counters

        return hedge_counters(MetricsRegistry())

    def test_hedge_masks_slow_replica(self):
        from keto_tpu.client import HedgePolicy, Hedger
        from keto_tpu.faults import FAULTS

        counters = self._counters()
        # exactly one armed stall: the primary attempt eats it, the
        # reissued duplicate sails through — CheckServicer.Check consults
        # this same site on entry
        FAULTS.arm_slow("replica.slow", sleep_ms=400, times=1)

        def replica_check():
            FAULTS.maybe_sleep("replica.slow")
            return True

        with Hedger(HedgePolicy(delay_s=0.05), counters=counters) as h:
            out = h.call(replica_check)
        assert out.result is True
        assert out.hedged is True and out.hedge_won is True
        assert out.elapsed_s < 0.35  # the 400ms stall never reached p99
        fired, won, wasted, suppressed = counters
        assert (fired.value, won.value, wasted.value) == (1, 1, 0)
        assert suppressed.value == 0

    def test_fast_primary_never_hedges(self):
        from keto_tpu.client import HedgePolicy, Hedger

        counters = self._counters()
        calls = []

        def fast():
            calls.append("primary")
            return 7

        with Hedger(HedgePolicy(delay_s=0.2), counters=counters) as h:
            out = h.call(fast)
        assert out.result == 7
        assert out.hedged is False
        assert calls == ["primary"]
        assert [c.value for c in counters] == [0, 0, 0, 0]

    def test_at_most_one_hedge_and_loser_discarded(self):
        from keto_tpu.client import HedgePolicy, Hedger

        counters = self._counters()
        started = []
        release = threading.Event()

        def primary():
            started.append("primary")
            release.wait(5)
            return "stale"

        def hedge():
            started.append("hedge")
            return "fresh"

        try:
            with Hedger(HedgePolicy(delay_s=0.02), counters=counters) as h:
                out = h.call(primary, hedge=hedge)
        finally:
            release.set()
        assert out.result == "fresh"  # the duplicate's answer was used,
        assert started == ["primary", "hedge"]  # and issued exactly once
        fired, won, wasted, suppressed = counters
        assert (fired.value, won.value, wasted.value) == (1, 1, 0)
        assert suppressed.value == 0

    def test_primary_win_after_hedge_counts_wasted(self):
        from keto_tpu.client import HedgePolicy, Hedger

        counters = self._counters()
        release = threading.Event()

        def primary():
            time.sleep(0.08)
            return "primary"

        def hedge():
            release.wait(5)
            return "hedge"

        try:
            with Hedger(HedgePolicy(delay_s=0.02), counters=counters) as h:
                out = h.call(primary, hedge=hedge)
        finally:
            release.set()
        assert out.result == "primary"
        assert out.hedged is True and out.hedge_won is False
        fired, won, wasted, suppressed = counters
        assert (fired.value, won.value, wasted.value) == (1, 0, 1)
        assert suppressed.value == 0


class TestEndpointRouter:
    """Health-aware routing (client/hedge.py EndpointRouter): decaying
    error penalties — a transiently failing endpoint returns to rotation
    after ~one half-life instead of eating a penalty box forever — plus
    /cluster/status health demotion and leader tracking across terms."""

    def _router(self, n=3, cool_off_s=1.0):
        from keto_tpu.client.hedge import EndpointRouter

        clock = [100.0]
        eps = [f"http://r{i}:1" for i in range(n)]
        return (
            EndpointRouter(
                eps, cool_off_s=cool_off_s, clock=lambda: clock[0]
            ),
            eps,
            clock,
        )

    def test_error_benches_then_decays_back(self):
        router, eps, clock = self._router(n=2, cool_off_s=1.0)
        router.observe_error(eps[0])
        # fresh error: score 1.0 -> benched, all picks avoid it
        assert router.snapshot()[eps[0]]["benched"] is True
        for _ in range(6):
            primary, _hedge = router.pick()
            assert primary == eps[1]
        # one half-life later the score is 0.5: recovered, no reset call
        clock[0] += 1.0
        snap = router.snapshot()[eps[0]]
        assert snap["benched"] is False
        assert snap["error_score"] == pytest.approx(0.5)
        assert any(router.pick()[0] == eps[0] for _ in range(4))

    def test_repeat_offender_benched_longer_never_forever(self):
        router, eps, clock = self._router(n=2, cool_off_s=1.0)
        for _ in range(8):
            router.observe_error(eps[0])
        score = router.snapshot()[eps[0]]["error_score"]
        assert score == pytest.approx(8.0)
        # one half-life halves it — still benched (4.0 >= 1.0) ...
        clock[0] += 1.0
        assert router.snapshot()[eps[0]]["benched"] is True
        # ... log2(8)=3 half-lives bring it to exactly 1.0; past that
        # the endpoint is back (bounded penalty, never permanent)
        clock[0] += 2.5
        assert router.snapshot()[eps[0]]["benched"] is False

    def test_error_score_is_capped(self):
        router, eps, clock = self._router(n=2, cool_off_s=1.0)
        for _ in range(100):
            router.observe_error(eps[0])
        assert router.snapshot()[eps[0]]["error_score"] <= 16.0
        # so even a long outage decays back within log2(16)=4 half-lives
        clock[0] += 4.01
        assert router.snapshot()[eps[0]]["benched"] is False

    def test_reads_never_stop_when_everything_is_benched(self):
        router, eps, clock = self._router(n=2)
        for e in eps:
            router.observe_error(e)
        primary, hedge = router.pick()
        assert primary in eps and hedge in eps and primary != hedge

    def test_red_health_demotes_like_errors(self):
        router, eps, clock = self._router(n=2)
        router.observe_status(
            {
                "members": [
                    {"instance_id": "r0", "read_url": eps[0],
                     "health": "red", "alive": True, "version": 9},
                    {"instance_id": "r1", "read_url": eps[1],
                     "health": "green", "alive": True, "version": 9},
                ]
            }
        )
        for _ in range(4):
            assert router.pick()[0] == eps[1]
        # the rollup also pre-warmed the freshness map
        assert router.snapshot()[eps[0]]["known_version"] == 9
        # a recovered rollup restores it
        router.observe_status(
            {
                "members": [
                    {"instance_id": "r0", "read_url": eps[0],
                     "health": "green", "alive": True},
                ]
            }
        )
        assert any(router.pick()[0] == eps[0] for _ in range(4))

    def test_leader_follows_hints_but_rejects_stale_terms(self):
        router, eps, clock = self._router(n=2)
        router.observe_leader(
            {"leader_id": "b", "term": 3,
             "read_url": eps[1], "write_url": "http://w1:2"}
        )
        assert router.leader()["write_url"] == "http://w1:2"
        # a fenced ex-leader's lower-term hint must not win back traffic
        router.observe_leader(
            {"leader_id": "a", "term": 2,
             "read_url": eps[0], "write_url": "http://w0:2"}
        )
        assert router.leader()["write_url"] == "http://w1:2"
        router.observe_leader(
            {"leader_id": "c", "term": 4,
             "read_url": eps[0], "write_url": "http://w0:2"}
        )
        assert router.leader()["term"] == 4

    def test_freshness_map_survives_a_term_change(self):
        router, eps, clock = self._router(n=2)
        router.observe_version(eps[0], 40)
        router.observe_version(eps[1], 55)
        router.observe_status(
            {"cluster": {"election": {"observed_term": 7}}, "members": []}
        )
        # snaptoken routing keeps honoring known versions mid-election:
        # versions are preserved across promotion (shared-WAL replay)
        assert router.pick(min_version=50)[0] == eps[1]
        assert router.snapshot()[eps[0]]["known_version"] == 40
