"""Durable write plane tests: WAL framing, atomic checkpoints, crash
recovery, and the recovery-beats-reingest acceptance bound.

The crash-fault drill proper (SIGKILL at every seeded fault site with
Check/Expand parity against a shadow oracle) lives in tools/soak.py
--restart; these tests pin the component contracts it builds on:

- a torn frame at the tail of the FINAL segment is an unacked write and
  is silently truncated; the same damage mid-log flags ``gap``
- a checkpoint write that dies mid-tmp-file leaves the previous
  checkpoint fully readable (tmp+rename atomicity)
- recovery = newest checkpoint + WAL-suffix replay, and is an order of
  magnitude faster than re-ingesting the tuples through the write path
"""

import os
import time

import pytest

from keto_tpu.faults import FAULTS, FaultInjected
from keto_tpu.graph import checkpoint as ckpt_mod
from keto_tpu.relationtuple import (
    RelationQuery,
    RelationTuple,
    SubjectID,
    SubjectSet,
)
from keto_tpu.store import (
    ColumnarTupleStore,
    DurableTupleStore,
    InMemoryTupleStore,
    WalError,
    WriteAheadLog,
    recover_store,
)

STORE_KINDS = {"memory": InMemoryTupleStore, "columnar": ColumnarTupleStore}


@pytest.fixture(autouse=True)
def _clean_faults():
    FAULTS.reset()
    yield
    FAULTS.reset()


@pytest.fixture(params=sorted(STORE_KINDS))
def kind(request):
    return request.param


def _t(i, rel="view"):
    return RelationTuple("n", f"o{i}", rel, SubjectID(f"u{i % 7}"))


def _tuples_of(store):
    resp, _ = store.get_relation_tuples(RelationQuery(namespace="n"))
    return sorted(resp, key=str)


# -- WAL framing --------------------------------------------------------------


class TestWalFormat:
    def test_append_replay_roundtrip(self, tmp_path):
        wal = WriteAheadLog(str(tmp_path))
        wal.append(1, [_t(0)], [])
        wal.append(
            2,
            [RelationTuple("n", "doc", "view", SubjectSet("n", "g", "member"))],
            [_t(0)],
        )
        wal.append(3, [], [])
        wal.close()

        records, stats = WriteAheadLog.replay(str(tmp_path))
        assert [r.version for r in records] == [1, 2, 3]
        assert records[0].inserted == [_t(0)]
        assert records[1].deleted == [_t(0)]
        assert isinstance(records[1].inserted[0].subject, SubjectSet)
        assert not stats.gap
        assert stats.torn_tail_bytes == 0

    def test_torn_tail_is_truncated_silently(self, tmp_path):
        wal = WriteAheadLog(str(tmp_path))
        wal.append(1, [_t(1)], [])
        wal.append(2, [_t(2)], [])
        wal.close()
        seg = os.path.join(
            str(tmp_path), sorted(os.listdir(str(tmp_path)))[-1]
        )
        with open(seg, "ab") as f:
            f.write(b"\x01\x02\x03")  # half a frame header

        records, stats = WriteAheadLog.replay(str(tmp_path))
        assert [r.version for r in records] == [1, 2]
        assert stats.torn_tail_bytes == 3
        assert not stats.gap

        # the append-side open truncates the torn tail so new frames never
        # land after garbage
        wal = WriteAheadLog(str(tmp_path))
        wal.append(3, [_t(3)], [])
        wal.close()
        records, stats = WriteAheadLog.replay(str(tmp_path))
        assert [r.version for r in records] == [1, 2, 3]
        assert stats.torn_tail_bytes == 0

    def test_mid_log_corruption_flags_gap(self, tmp_path):
        wal = WriteAheadLog(str(tmp_path))
        for v in range(1, 4):
            wal.append(v, [_t(v)], [])
        wal.close()
        seg = os.path.join(
            str(tmp_path), sorted(os.listdir(str(tmp_path)))[-1]
        )
        with open(seg, "r+b") as f:
            f.seek(20)  # inside the first frame's payload
            f.write(b"\xff")

        records, stats = WriteAheadLog.replay(str(tmp_path))
        assert stats.gap  # acked records may be unreachable
        assert len(records) < 3

    def test_rotation_and_prune(self, tmp_path):
        wal = WriteAheadLog(str(tmp_path), segment_bytes=1)  # every append rotates
        for v in range(1, 6):
            wal.append(v, [_t(v)], [])
        segs = [n for n in os.listdir(str(tmp_path)) if n.endswith(".seg")]
        assert len(segs) == 5

        removed = wal.prune_upto(3)
        assert removed == 3  # segments holding versions 1..3 are redundant
        records, stats = WriteAheadLog.replay(str(tmp_path))
        assert [r.version for r in records] == [4, 5]
        assert not stats.gap
        wal.close()

    def test_sync_policies(self, tmp_path):
        for policy in ("always", "interval", "off"):
            d = str(tmp_path / policy)
            wal = WriteAheadLog(d, sync=policy, sync_interval_ms=5)
            wal.append(1, [_t(1)], [])
            wal.close()
            records, stats = WriteAheadLog.replay(d)
            assert [r.version for r in records] == [1]
        with pytest.raises(WalError):
            WriteAheadLog(str(tmp_path / "bad"), sync="sometimes")


class TestWalFaults:
    def test_torn_write_fault_loses_only_the_unacked_record(self, tmp_path):
        wal = WriteAheadLog(str(tmp_path))
        wal.append(1, [_t(1)], [])
        FAULTS.arm("wal.torn_write")
        with pytest.raises(FaultInjected):
            wal.append(2, [_t(2)], [])
        records, stats = WriteAheadLog.replay(str(tmp_path))
        assert [r.version for r in records] == [1]
        assert stats.torn_tail_bytes > 0
        assert not stats.gap

    def test_corrupt_crc_fault_record_is_refused(self, tmp_path):
        wal = WriteAheadLog(str(tmp_path))
        wal.append(1, [_t(1)], [])
        FAULTS.arm("wal.corrupt_crc")
        with pytest.raises(FaultInjected):
            wal.append(2, [_t(2)], [])
        records, stats = WriteAheadLog.replay(str(tmp_path))
        assert [r.version for r in records] == [1]
        assert stats.bad_frames == 1
        assert not stats.gap  # damage sits at the final tail: unacked

    def test_crash_after_append_record_survives(self, tmp_path):
        wal = WriteAheadLog(str(tmp_path))
        wal.append(1, [_t(1)], [])
        FAULTS.arm("wal.crash_after_append")
        with pytest.raises(FaultInjected):
            wal.append(2, [_t(2)], [])
        records, _ = WriteAheadLog.replay(str(tmp_path))
        # durable-but-unacked: recovery may legitimately surface it
        assert [r.version for r in records] == [1, 2]


# -- checkpoints --------------------------------------------------------------


class TestCheckpoint:
    def _build(self, kind):
        store = STORE_KINDS[kind]()
        store.write_relation_tuples(*[_t(i) for i in range(20)])
        store.write_relation_tuples(
            RelationTuple("n", "doc", "view", SubjectSet("n", "g", "member"))
        )
        store.delete_relation_tuples(_t(3), _t(7))
        return store

    def test_roundtrip(self, tmp_path, kind):
        store = self._build(kind)
        path = ckpt_mod.write_checkpoint(str(tmp_path), store)
        assert os.path.basename(path) == f"ckpt-{store.version:020d}.npz"

        fresh = STORE_KINDS[kind]()
        ckpt = ckpt_mod.load_latest(str(tmp_path))
        ckpt.restore_into(fresh)
        assert fresh.version == store.version
        assert len(fresh) == len(store)
        assert _tuples_of(fresh) == _tuples_of(store)
        # the restored store must keep working as a mutable store
        fresh.write_relation_tuples(_t(99))
        assert fresh.version == store.version + 1

    def test_crash_mid_write_keeps_previous_checkpoint(self, tmp_path, kind):
        store = self._build(kind)
        ckpt_mod.write_checkpoint(str(tmp_path), store)
        v1 = store.version
        store.write_relation_tuples(_t(50))

        FAULTS.arm("checkpoint.crash_mid_write")
        with pytest.raises(FaultInjected):
            ckpt_mod.write_checkpoint(str(tmp_path), store)

        ckpt = ckpt_mod.load_latest(str(tmp_path))
        assert ckpt is not None and ckpt.version == v1  # previous survives
        # next successful write supersedes it and sweeps the tmp litter
        ckpt_mod.write_checkpoint(str(tmp_path), store)
        assert ckpt_mod.load_latest(str(tmp_path)).version == store.version
        assert not [n for n in os.listdir(str(tmp_path)) if ".tmp." in n]

    def test_damaged_checkpoint_is_skipped(self, tmp_path, kind):
        store = self._build(kind)
        ckpt_mod.write_checkpoint(str(tmp_path), store, keep=5)
        v1 = store.version
        store.write_relation_tuples(_t(51))
        newest = ckpt_mod.write_checkpoint(str(tmp_path), store, keep=5)
        with open(newest, "r+b") as f:
            f.truncate(os.path.getsize(newest) // 2)

        ckpt = ckpt_mod.load_latest(str(tmp_path))
        assert ckpt.version == v1
        assert ckpt.meta.get("skipped_damaged")

    @staticmethod
    def _tamper_payload(path):
        """Rewrite the npz with one payload value changed but the OLD
        meta blob kept: the zip container stays structurally valid, so
        only the sha256 verification can catch the damage (a truncation
        test would pass on zip CRCs alone)."""
        import numpy as np

        with np.load(path, allow_pickle=False) as npz:
            arrays = {n: npz[n] for n in npz.files}
        for name, arr in sorted(arrays.items()):
            if name != "meta" and arr.dtype.kind in "iu" and arr.size:
                arr = arr.copy()
                arr.flat[0] ^= 1
                arrays[name] = arr
                break
        else:
            raise AssertionError("no integer payload array to tamper")
        np.savez(path.removesuffix(".npz"), **arrays)

    def test_sha256_catches_silent_payload_corruption(self, tmp_path, kind):
        store = self._build(kind)
        ckpt_mod.write_checkpoint(str(tmp_path), store, keep=5)
        v1 = store.version
        store.write_relation_tuples(_t(52))
        newest = ckpt_mod.write_checkpoint(str(tmp_path), store, keep=5)
        self._tamper_payload(newest)

        # the damaged checkpoint must never load silently
        with pytest.raises(ckpt_mod.CheckpointError, match="sha256"):
            ckpt_mod.load_checkpoint(newest)
        # recovery falls back to the older, intact checkpoint
        ckpt = ckpt_mod.load_latest(str(tmp_path))
        assert ckpt.version == v1
        assert ckpt.meta.get("skipped_damaged")

    def test_pre_sha256_checkpoints_still_load(self, tmp_path, kind):
        """Checkpoints written before the sha256 field existed (or by an
        older binary) must load unverified rather than fail."""
        import json as _json

        import numpy as np

        store = self._build(kind)
        path = ckpt_mod.write_checkpoint(str(tmp_path), store)
        with np.load(path, allow_pickle=False) as npz:
            arrays = {n: npz[n] for n in npz.files}
        meta = _json.loads(bytes(arrays["meta"]).decode("utf-8"))
        meta.pop("sha256")
        arrays["meta"] = np.frombuffer(
            _json.dumps(meta).encode("utf-8"), dtype=np.uint8
        )
        np.savez(path.removesuffix(".npz"), **arrays)

        fresh = STORE_KINDS[kind]()
        ckpt_mod.load_checkpoint(path).restore_into(fresh)
        assert _tuples_of(fresh) == _tuples_of(store)


# -- durable wrapper + recovery ----------------------------------------------


class TestDurableRecovery:
    def _durable(self, tmp_path, kind, **kw):
        kw.setdefault("checkpoint_interval_versions", 10**9)
        kw.setdefault("checkpoint_interval_s", 0.0)
        return DurableTupleStore(
            STORE_KINDS[kind](), str(tmp_path / "wal"), **kw
        )

    def test_recovery_replays_the_wal(self, tmp_path, kind):
        store = self._durable(tmp_path, kind)
        store.write_relation_tuples(*[_t(i) for i in range(10)])
        store.delete_relation_tuples(_t(2))
        store.transact_relation_tuples([_t(77)], [_t(5)])
        expect, expect_version = _tuples_of(store), store.version
        # no close: simulate a crash (sync=always has already fsynced)

        fresh = STORE_KINDS[kind]()
        rep = recover_store(
            fresh, str(tmp_path / "wal"), str(tmp_path / "wal" / "checkpoints")
        )
        assert not rep.gap
        assert rep.replayed_deltas == 3
        assert rep.final_version == expect_version
        assert fresh.version == expect_version
        assert _tuples_of(fresh) == expect

    def test_recovery_is_checkpoint_plus_wal_suffix(self, tmp_path, kind):
        store = self._durable(tmp_path, kind)
        store.write_relation_tuples(*[_t(i) for i in range(8)])
        path = store.checkpoint_now()
        assert path is not None
        ckpt_version = store.last_checkpoint_version()
        store.write_relation_tuples(_t(100))
        store.delete_relation_tuples(_t(1))
        expect, expect_version = _tuples_of(store), store.version

        fresh = STORE_KINDS[kind]()
        rep = recover_store(
            fresh, str(tmp_path / "wal"), str(tmp_path / "wal" / "checkpoints")
        )
        assert not rep.gap
        assert rep.checkpoint_version == ckpt_version
        assert rep.replayed_deltas == 2  # only the suffix replays
        assert rep.final_version == expect_version
        assert _tuples_of(fresh) == expect

    def test_restart_reopens_cleanly(self, tmp_path, kind):
        store = self._durable(tmp_path, kind)
        store.write_relation_tuples(*[_t(i) for i in range(5)])
        v = store.version
        store.close_durable()  # cuts the final checkpoint

        store2 = self._durable(tmp_path, kind)
        assert store2.recovery.checkpoint_version == v
        assert store2.recovery.replayed_deltas == 0
        assert store2.version == v
        store2.write_relation_tuples(_t(200))
        assert store2.version == v + 1
        store2.close_durable()

    def test_fail_stop_after_append_failure(self, tmp_path, kind):
        store = self._durable(tmp_path, kind)
        store.write_relation_tuples(_t(1))
        FAULTS.arm("wal.torn_write")
        with pytest.raises(FaultInjected):
            store.write_relation_tuples(_t(2))
        # the wrapper refuses further writes instead of acking unlogged
        # mutations
        with pytest.raises(WalError):
            store.write_relation_tuples(_t(3))

    def test_bulk_load_cuts_synchronous_checkpoint(self, tmp_path):
        store = self._durable(tmp_path, "columnar")
        src = [("n", f"o{i}", "view") for i in range(500)]
        dst = [(f"u{i % 11}",) for i in range(500)]
        store.bulk_load_edges(src, dst)
        assert store.last_checkpoint_version() == store.version

        fresh = ColumnarTupleStore()
        rep = recover_store(
            fresh, str(tmp_path / "wal"), str(tmp_path / "wal" / "checkpoints")
        )
        assert not rep.gap
        assert len(fresh) == len(store)
        assert fresh.version == store.version

    def test_bulk_marker_without_checkpoint_degrades_loudly(self, tmp_path):
        store = self._durable(tmp_path, "columnar")
        FAULTS.arm("checkpoint.crash_mid_write")
        with pytest.raises(FaultInjected):
            store.bulk_load_edges([("n", "o", "view")], [("u1",)])

        # the WAL holds an unreplayable bulk marker and no checkpoint
        # covers it: recovery must flag the gap, not serve silently wrong
        fresh = ColumnarTupleStore()
        rep = recover_store(
            fresh, str(tmp_path / "wal"), str(tmp_path / "wal" / "checkpoints")
        )
        assert rep.gap
        assert any("bulk" in n for n in rep.notes)
        assert rep.final_version == store.version  # snaptokens stay monotonic

    def test_background_checkpoint_trigger(self, tmp_path, kind):
        store = self._durable(
            tmp_path, kind, checkpoint_interval_versions=5
        )
        for i in range(7):
            store.write_relation_tuples(_t(i))
        deadline = time.monotonic() + 10.0
        while (
            store.last_checkpoint_version() == 0
            and time.monotonic() < deadline
        ):
            time.sleep(0.01)
        assert store.last_checkpoint_version() >= 5
        store.close_durable()


# -- acceptance: recovery beats re-ingest -------------------------------------


class TestRecoverySpeed:
    def _measure(self, tmp_path, n, reingest_sample):
        """(recovery_s, estimated_full_reingest_s) at n tuples."""
        store = DurableTupleStore(
            ColumnarTupleStore(),
            str(tmp_path / "wal"),
            checkpoint_interval_versions=10**9,
            checkpoint_interval_s=0.0,
        )
        src = [("n", f"o{i // 8}", "view") for i in range(n)]
        dst = [(f"u{i % 8}",) for i in range(n)]
        store.bulk_load_edges(src, dst)  # cuts the checkpoint

        t0 = time.perf_counter()
        fresh = ColumnarTupleStore()
        rep = recover_store(
            fresh, str(tmp_path / "wal"), str(tmp_path / "wal" / "checkpoints")
        )
        recovery_s = time.perf_counter() - t0
        assert not rep.gap
        assert len(fresh) == n

        # full re-ingest = pushing every tuple back through the write
        # path; measure a sample and scale (the write path is linear)
        sample = [
            RelationTuple("n", f"o{i // 8}", "view", SubjectID(f"u{i % 8}"))
            for i in range(reingest_sample)
        ]
        target = ColumnarTupleStore()
        t0 = time.perf_counter()
        for at in range(0, reingest_sample, 500):
            target.write_relation_tuples(*sample[at:at + 500])
        reingest_s = (time.perf_counter() - t0) * (n / reingest_sample)
        return recovery_s, reingest_s

    def test_recovery_beats_reingest(self, tmp_path):
        recovery_s, reingest_s = self._measure(
            tmp_path, n=50_000, reingest_sample=50_000
        )
        assert recovery_s * 3 <= reingest_s, (
            f"recovery {recovery_s:.3f}s vs re-ingest {reingest_s:.3f}s"
        )

    @pytest.mark.slow
    def test_recovery_10x_faster_than_reingest_at_1m(self, tmp_path):
        """ISSUE acceptance bound: checkpoint+WAL recovery at 1M tuples is
        >= 10x faster than re-ingesting through the write path."""
        recovery_s, reingest_s = self._measure(
            tmp_path, n=1_000_000, reingest_sample=100_000
        )
        assert recovery_s * 10 <= reingest_s, (
            f"recovery {recovery_s:.3f}s vs re-ingest {reingest_s:.3f}s"
        )
