"""Graph encoding layer: vocab interning, COO/CSR encode, incremental deltas."""

import numpy as np

from keto_tpu.graph import GraphSnapshot, NodeVocab, SnapshotBuilder, SnapshotManager
from keto_tpu.graph.vocab import id_key, set_key
from keto_tpu.relationtuple import (
    RelationTuple,
    SubjectID,
    SubjectSet,
)
from keto_tpu.store import InMemoryTupleStore


def t(s: str) -> RelationTuple:
    return RelationTuple.from_string(s)


import pytest


@pytest.fixture
def store():
    # no namespace validation: these tests exercise encoding, not config
    return InMemoryTupleStore()


class TestVocab:
    def test_intern_stable(self):
        v = NodeVocab()
        a = v.intern(set_key("n", "o", "r"))
        b = v.intern(id_key("user"))
        assert v.intern(set_key("n", "o", "r")) == a
        assert v.intern(id_key("user")) == b
        assert a != b
        assert len(v) == 2

    def test_id_and_set_keys_disjoint(self):
        v = NodeVocab()
        # a subject id that textually looks like a set must not collide
        a = v.intern(id_key("n:o#r"))
        b = v.intern(set_key("n", "o", "r"))
        assert a != b

    def test_subject_roundtrip(self):
        v = NodeVocab()
        s1 = SubjectID(id="alice")
        s2 = SubjectSet(namespace="files", object="readme", relation="viewer")
        assert v.subject_of(v.intern_subject(s1)) == s1
        assert v.subject_of(v.intern_subject(s2)) == s2


class TestSnapshotBuilder:
    def test_empty(self):
        snap = SnapshotBuilder().build([], version=0)
        assert snap.num_edges == 0
        assert snap.padded_nodes >= 1
        assert (snap.src == snap.dummy_node).all()

    def test_edges_and_padding(self):
        tuples = [t("n:o#r@alice"), t("n:o#r@(n:g#member)"), t("n:g#member@bob")]
        snap = SnapshotBuilder().build(tuples, version=7)
        assert snap.version == 7
        assert snap.num_edges == 3
        # power-of-two padding with dummy fill
        assert snap.padded_edges & (snap.padded_edges - 1) == 0
        assert (snap.src[3:] == snap.dummy_node).all()
        # o#r has two successors: alice and the g#member set node
        orr = snap.node_for_set("n", "o", "r")
        succ = snap.out_neighbors(orr)
        assert len(succ) == 2
        keys = {snap.vocab.key(int(x)) for x in succ}
        assert keys == {("alice",), ("n", "g", "member")}

    def test_unknown_subject_maps_to_dummy(self):
        snap = SnapshotBuilder().build([t("n:o#r@alice")], version=1)
        assert snap.node_for_subject(SubjectID(id="nobody")) == snap.dummy_node
        assert snap.node_for_set("n", "nope", "r") == snap.dummy_node

    def test_csr_matches_coo(self):
        rng = np.random.default_rng(0)
        tuples = [
            t(f"n:o{rng.integers(20)}#r@u{rng.integers(30)}") for _ in range(200)
        ]
        tuples = list(dict.fromkeys(tuples))
        snap = SnapshotBuilder().build(tuples, version=1)
        indptr, indices = snap.csr()
        # every COO edge appears under its source's CSR row
        for s, d in zip(snap.src[: snap.num_edges], snap.dst[: snap.num_edges]):
            row = indices[indptr[s] : indptr[s + 1]]
            assert d in row


class TestSnapshotManager:
    def test_tracks_store_version(self, store):
        mgr = SnapshotManager(store)
        assert mgr.snapshot().num_edges == 0
        store.write_relation_tuples(t("n:o#r@alice"))
        snap = mgr.snapshot()
        assert snap.num_edges == 1
        assert snap.version == store.version

    def test_incremental_insert_keeps_node_ids(self, store):
        store.write_relation_tuples(t("n:o#r@alice"))
        mgr = SnapshotManager(store)
        snap1 = mgr.snapshot()
        nid = snap1.node_for_set("n", "o", "r")
        store.write_relation_tuples(t("n:o#r@bob"))
        snap2 = mgr.snapshot()
        # applied incrementally: same vocab object, id stable, no rebuild
        assert snap2.vocab is snap1.vocab
        assert snap2.node_for_set("n", "o", "r") == nid
        assert snap2.num_edges == 2

    def test_delete_triggers_rebuild(self, store):
        store.write_relation_tuples(t("n:o#r@alice"), t("n:o#r@bob"))
        mgr = SnapshotManager(store)
        assert mgr.snapshot().num_edges == 2
        store.delete_relation_tuples(t("n:o#r@alice"))
        snap = mgr.snapshot()
        assert snap.num_edges == 1
        orr = snap.node_for_set("n", "o", "r")
        succ = {snap.vocab.key(int(x)) for x in snap.out_neighbors(orr)}
        assert succ == {("bob",)}

    def test_capacity_growth_rebuilds(self, store):
        mgr = SnapshotManager(store, min_nodes=4, min_edges=4)
        for i in range(50):
            store.write_relation_tuples(t(f"n:o#r@user{i}"))
        snap = mgr.snapshot()
        assert snap.num_edges == 50
        assert snap.padded_edges >= 64

    def test_duplicate_write_is_noop_edgewise(self, store):
        store.write_relation_tuples(t("n:o#r@alice"))
        mgr = SnapshotManager(store)
        store.write_relation_tuples(t("n:o#r@alice"))  # dedup in store
        snap = mgr.snapshot()
        assert snap.num_edges == 1
        assert snap.version == store.version
