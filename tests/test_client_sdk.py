"""Client SDK e2e: the RestClient/GrpcClient package must drive a live
server the way the reference's generated swagger SDK + gRPC clients drive
theirs (reference internal/e2e/sdk_client_test.go / grpc_client_test.go),
and the registry factories must stand up working registries (reference
registry_factory.go:56-95)."""

import pytest

from keto_tpu.client import GrpcClient, RestClient
from keto_tpu.driver.factory import (
    new_sqlite_test_registry,
    new_test_registry,
)
from keto_tpu.relationtuple import (
    RelationQuery,
    RelationTuple,
    SubjectSet,
)
from keto_tpu.utils.errors import ErrMalformedInput, ErrNotFound
from tests.test_api_server import ServerFixture


@pytest.fixture(scope="module")
def server():
    s = ServerFixture(new_test_registry(namespaces=("videos",)))
    yield s
    s.stop()


@pytest.fixture
def rest(server):
    with RestClient(
        f"http://127.0.0.1:{server.read_port}",
        f"http://127.0.0.1:{server.write_port}",
    ) as c:
        yield c
        # leave a clean store for the next test
        c.delete_relation_tuples(RelationQuery(namespace="videos"))


class TestRestClient:
    def test_crud_check_expand_flow(self, rest):
        rest.create_relation_tuple("videos:/cats#owner@cat lady")
        rest.create_relation_tuple(
            "videos:/cats/1.mp4#view@(videos:/cats#owner)"
        )
        assert rest.check("videos:/cats/1.mp4#view@cat lady").allowed
        assert not rest.check("videos:/cats/1.mp4#view@dog guy").allowed
        assert rest.batch_check(
            [
                "videos:/cats/1.mp4#view@cat lady",
                "videos:/cats/1.mp4#view@dog guy",
            ]
        ) == [True, False]

        tree = rest.expand(
            SubjectSet(namespace="videos", object="/cats/1.mp4", relation="view")
        )
        assert tree is not None and "cat lady" in str(tree)

        page = rest.get_relation_tuples(RelationQuery(namespace="videos"))
        assert len(page.relation_tuples) == 2
        assert page.next_page_token == ""

    def test_pagination_iterator(self, rest):
        for i in range(7):
            rest.create_relation_tuple(f"videos:v{i}#view@u{i}")
        seen = list(
            rest.iter_relation_tuples(
                RelationQuery(namespace="videos"), page_size=3
            )
        )
        assert len(seen) == 7

    def test_patch_transaction(self, rest):
        t1 = RelationTuple.from_string("videos:a#r@u1")
        t2 = RelationTuple.from_string("videos:b#r@u2")
        rest.patch_relation_tuples(insert=[t1, t2])
        rest.patch_relation_tuples(insert=[], delete=[t1])
        page = rest.get_relation_tuples(RelationQuery(namespace="videos"))
        assert [t.object for t in page.relation_tuples] == ["b"]

    def test_error_taxonomy(self, rest):
        with pytest.raises(ErrNotFound):
            rest.create_relation_tuple("nope:x#r@u")  # unknown namespace
        with pytest.raises(ErrMalformedInput):
            rest.get_relation_tuples(
                RelationQuery(namespace="videos"), page_token="garbage!!"
            )

    def test_health_version_metrics(self, rest):
        assert rest.alive() and rest.ready()
        assert rest.version()
        assert "keto_checks_total" in rest.metrics()


class TestGrpcClient:
    def test_check_and_expand(self, server, rest):
        rest.create_relation_tuple("videos:/d#view@eve")
        with GrpcClient(
            f"127.0.0.1:{server.read_port}",
            f"127.0.0.1:{server.write_port}",
        ) as g:
            res = g.check("videos:/d#view@eve")
            assert res.allowed and res.snaptoken
            assert not g.check("videos:/d#view@mallory").allowed
            tree = g.expand(
                SubjectSet(namespace="videos", object="/d", relation="view")
            )
            assert tree is not None

    def test_grpc_batch_check(self, server, rest):
        rest.create_relation_tuple("videos:/b#view@eve")
        with GrpcClient(f"127.0.0.1:{server.read_port}") as g:
            assert g.batch_check(
                [
                    "videos:/b#view@eve",
                    "videos:/b#view@nobody",
                    "videos:/b#view@eve",
                ]
            ) == [True, False, True]


class TestRegistryFactories:
    def test_sqlite_test_registry_automigrates(self, tmp_path):
        reg = new_sqlite_test_registry(str(tmp_path / "t.db"))
        store = reg.store()
        store.write_relation_tuples(
            RelationTuple.from_string("videos:o#r@alice")
        )
        assert len(store.get_relation_tuples(RelationQuery())[0]) == 1

    def test_test_registry_engine_default(self):
        reg = new_test_registry()
        from keto_tpu.engine.closure import ClosureCheckEngine

        assert isinstance(reg.check_engine(), ClosureCheckEngine)
