"""The reference's e2e pattern: ONE behavioral case suite executed through
every client implementation against a live server (reference
internal/e2e/full_suit_test.go:45-86 runs runCases through gRPC, raw REST,
the CLI binary, and the generated SDK). Same matrix here: GrpcClient,
RestClient (the SDK), raw httpx REST, and the click CLI — each adapter
exposes create/check/expand/list/delete and must produce identical
behavior over the same server."""

import json
import tempfile

import httpx
import pytest
from click.testing import CliRunner

from keto_tpu.cli import cli
from keto_tpu.client import GrpcClient, RestClient
from keto_tpu.driver.factory import (
    new_sqlite_test_registry,
    new_test_registry,
)
from keto_tpu.relationtuple import RelationQuery, RelationTuple, SubjectSet
from tests.test_api_server import ServerFixture


def t(s: str) -> RelationTuple:
    return RelationTuple.from_string(s)


# the reference crosses its one case suite with every DSN
# (internal/e2e/full_suit_test.go:45-86 x dsn_testutils); here the server
# axis is {store backend} x {worker pool size} — workers=3 exercises the
# fork pool (memory/columnar) and the spawn pool (sqlite) end-to-end
SERVER_CONFIGS = [
    ("memory", 1),
    ("memory", 3),
    ("columnar", 1),
    ("columnar", 3),
    ("sqlite", 1),
    ("sqlite", 3),
]


def _registry_for(store_kind: str, workers: int, tmpdir: str):
    values = {
        "serve": {
            "read": {"port": 0, "host": "127.0.0.1", "workers": workers},
            "write": {"port": 0, "host": "127.0.0.1"},
        },
        "log": {"level": "error"},
    }
    if store_kind == "sqlite":
        return new_sqlite_test_registry(
            f"{tmpdir}/e2e.db", namespaces=("videos",), values=values
        )
    if store_kind == "columnar":
        values["dsn"] = "columnar"
        return new_test_registry(namespaces=("videos",), values=values)
    return new_test_registry(namespaces=("videos",), values=values)


@pytest.fixture(
    scope="module",
    params=SERVER_CONFIGS,
    ids=[f"{s}-w{w}" for s, w in SERVER_CONFIGS],
)
def server(request):
    store_kind, workers = request.param
    with tempfile.TemporaryDirectory() as tmpdir:
        s = ServerFixture(_registry_for(store_kind, workers, tmpdir))
        yield s
        s.stop()


class GrpcAdapter:
    name = "grpc"

    def __init__(self, server):
        self.c = GrpcClient(
            f"127.0.0.1:{server.read_port}",
            f"127.0.0.1:{server.write_port}",
        )

    def create(self, tup):
        assert self.c.transact(insert=[tup])  # snaptoken returned

    def check(self, tup):
        return self.c.check(tup).allowed

    def expand_subjects(self, ss):
        tree = self.c.expand(ss)
        return "" if tree is None else str(tree)

    def list_count(self, namespace):
        from keto_tpu.api import read_service_pb2

        total, token = 0, ""
        while True:
            resp = self.c.read_service.ListRelationTuples(
                read_service_pb2.ListRelationTuplesRequest(
                    query=read_service_pb2.ListRelationTuplesRequest.Query(
                        namespace=namespace
                    ),
                    page_token=token,
                )
            )
            total += len(resp.relation_tuples)
            token = resp.next_page_token
            if not token:
                return total

    def delete_all(self, namespace):
        from keto_tpu.api import read_service_pb2, write_service_pb2

        self.c.write_service.DeleteRelationTuples(
            write_service_pb2.DeleteRelationTuplesRequest(
                query=write_service_pb2.DeleteRelationTuplesRequest.Query(
                    namespace=namespace
                )
            )
        )

    def close(self):
        self.c.close()


class SdkAdapter:
    name = "sdk"

    def __init__(self, server):
        self.c = RestClient(
            f"http://127.0.0.1:{server.read_port}",
            f"http://127.0.0.1:{server.write_port}",
        )

    def create(self, tup):
        self.c.create_relation_tuple(tup)

    def check(self, tup):
        return self.c.check(tup).allowed

    def expand_subjects(self, ss):
        tree = self.c.expand(ss)
        return "" if tree is None else str(tree)

    def list_count(self, namespace):
        return len(
            list(
                self.c.iter_relation_tuples(RelationQuery(namespace=namespace))
            )
        )

    def delete_all(self, namespace):
        self.c.delete_relation_tuples(RelationQuery(namespace=namespace))

    def close(self):
        self.c.close()


class RawRestAdapter:
    name = "rest"

    def __init__(self, server):
        self.read = f"http://127.0.0.1:{server.read_port}"
        self.write = f"http://127.0.0.1:{server.write_port}"
        self.http = httpx.Client(timeout=30)

    def create(self, tup):
        r = self.http.put(
            f"{self.write}/relation-tuples", json=t(tup).to_dict()
        )
        assert r.status_code == 201, r.text

    def check(self, tup):
        tu = t(tup)
        params = {
            "namespace": tu.namespace,
            "object": tu.object,
            "relation": tu.relation,
        }
        s = tu.subject
        if hasattr(s, "id"):
            params["subject_id"] = s.id
        else:
            params.update(
                {
                    "subject_set.namespace": s.namespace,
                    "subject_set.object": s.object,
                    "subject_set.relation": s.relation,
                }
            )
        r = self.http.get(f"{self.read}/check", params=params)
        assert r.status_code in (200, 403)
        return r.json()["allowed"]

    def expand_subjects(self, ss):
        r = self.http.get(
            f"{self.read}/expand",
            params={
                "namespace": ss.namespace,
                "object": ss.object,
                "relation": ss.relation,
            },
        )
        assert r.status_code == 200
        return json.dumps(r.json())

    def list_count(self, namespace):
        total, token = 0, ""
        while True:
            r = self.http.get(
                f"{self.read}/relation-tuples",
                params={"namespace": namespace, "page_token": token},
            )
            doc = r.json()
            total += len(doc["relation_tuples"])
            token = doc["next_page_token"]
            if not token:
                return total

    def delete_all(self, namespace):
        r = self.http.delete(
            f"{self.write}/relation-tuples", params={"namespace": namespace}
        )
        assert r.status_code == 204

    def close(self):
        self.http.close()


class CliAdapter:
    name = "cli"

    def __init__(self, server):
        self.r = CliRunner()
        self.remotes = [
            "--read-remote", f"127.0.0.1:{server.read_port}",
            "--write-remote", f"127.0.0.1:{server.write_port}",
        ]

    def _run(self, args, input=None, ok=(0,)):
        res = self.r.invoke(cli, self.remotes + args, input=input)
        assert res.exit_code in ok, res.output
        return res

    def create(self, tup):
        doc = json.dumps(t(tup).to_dict())
        self._run(["relation-tuple", "create", "-"], input=doc)

    def check(self, tup):
        tu = t(tup)
        sub = str(tu.subject)
        res = self._run(
            ["check", sub, tu.relation, tu.namespace, tu.object], ok=(0, 1)
        )
        return res.exit_code == 0

    def expand_subjects(self, ss):
        res = self._run(["expand", ss.relation, ss.namespace, ss.object])
        return res.output

    def list_count(self, namespace):
        total, token = 0, ""
        while True:
            args = ["relation-tuple", "get", "--namespace", namespace,
                    "--format", "json"]
            if token:
                args += ["--page-token", token]
            res = self._run(args)
            doc = json.loads(res.output)
            total += len(doc["relation_tuples"])
            token = doc.get("next_page_token", "")
            if not token:
                return total

    def delete_all(self, namespace):
        self._run(
            ["relation-tuple", "delete-all", "--namespace", namespace,
             "--force"]
        )

    def close(self):
        pass


ADAPTERS = [GrpcAdapter, SdkAdapter, RawRestAdapter, CliAdapter]


@pytest.fixture(params=ADAPTERS, ids=lambda a: a.name)
def client(request, server):
    c = request.param(server)
    yield c
    c.delete_all("videos")
    c.close()


def run_cases(client):
    """The shared behavioral cases (reference cases_test.go:21-202)."""
    # direct + two-level indirection
    client.create("videos:/cats#owner@cat lady")
    client.create("videos:/cats/1.mp4#owner@(videos:/cats#owner)")
    client.create("videos:/cats/1.mp4#view@(videos:/cats/1.mp4#owner)")
    assert client.check("videos:/cats#owner@cat lady")
    assert client.check("videos:/cats/1.mp4#owner@cat lady")
    assert client.check("videos:/cats/1.mp4#view@cat lady")
    assert not client.check("videos:/cats/1.mp4#view@dog guy")
    # unknown object/relation/subject deny
    assert not client.check("videos:/dogs#view@cat lady")
    # expand reaches the root subject
    out = client.expand_subjects(
        SubjectSet(namespace="videos", object="/cats/1.mp4", relation="view")
    )
    assert "cat lady" in out
    # listing sees exactly what was written
    assert client.list_count("videos") == 3
    # idempotent duplicate write
    client.create("videos:/cats#owner@cat lady")
    assert client.list_count("videos") == 3
    # delete-all empties the namespace and checks flip
    client.delete_all("videos")
    assert client.list_count("videos") == 0
    assert not client.check("videos:/cats#owner@cat lady")


def test_cases_through_every_client(client):
    run_cases(client)
