"""Pipelined dispatch vs host oracle: the multi-stage batcher (encode ->
launch -> decode, >=2 batches in flight, encoded-request cache) must answer
exactly like the host BFS CheckEngine under concurrent mixed-size traffic —
the ISSUE-2 acceptance drill. Also covers cache invalidation across writes,
the check_batch bulk result cache, and the /pipeline stats surface."""

import threading

import pytest

from keto_tpu.engine import CheckEngine
from keto_tpu.engine.batcher import CheckBatcher
from keto_tpu.engine.cache import CheckResultCache
from keto_tpu.engine.device import DeviceCheckEngine
from keto_tpu.graph import SnapshotManager
from keto_tpu.relationtuple import RelationTuple
from keto_tpu.store import InMemoryTupleStore
from keto_tpu.telemetry import MetricsRegistry


def t(s: str) -> RelationTuple:
    return RelationTuple.from_string(s)


@pytest.fixture
def store():
    s = InMemoryTupleStore()
    # a small layered graph: direct grants, one- and two-level nesting,
    # a cycle, and enough distinct objects that concurrent batches span
    # multiple pow2 buckets
    tuples = []
    for i in range(24):
        tuples.append(t(f"n:doc{i}#view@(n:group{i % 6}#member)"))
    for g in range(6):
        tuples.append(t(f"n:group{g}#member@(n:team{g % 3}#member)"))
        tuples.append(t(f"n:group{g}#member@direct{g}"))
    for m in range(3):
        tuples.append(t(f"n:team{m}#member@alice{m}"))
    tuples.append(t("n:cyc#r@(n:cyc2#r)"))
    tuples.append(t("n:cyc2#r@(n:cyc#r)"))
    s.write_relation_tuples(*tuples)
    return s


def _workload():
    reqs = []
    for i in range(24):
        for who in ("alice0", "alice1", "alice2", "direct3", "nobody"):
            reqs.append(t(f"n:doc{i}#view@{who}"))
    reqs.append(t("n:cyc#r@alice0"))
    reqs.append(t("n:cyc#r@(n:cyc2#r)"))
    return reqs


@pytest.fixture
def pipelined(store):
    mgr = SnapshotManager(store)
    engine = DeviceCheckEngine(mgr, max_depth=5)
    b = CheckBatcher(
        engine,
        window_s=0.0005,
        metrics=MetricsRegistry(),
        pipeline_depth=2,
        encode_workers=2,
        encoded_cache_size=4096,
    )
    yield b
    b.close()


class TestPipelineParity:
    def test_batcher_is_pipelined(self, pipelined):
        assert pipelined.pipelined is True
        assert len(pipelined._threads) == 4  # 2 encode + launch + decode

    def test_concurrent_mixed_batches_match_host_oracle(
        self, store, pipelined
    ):
        oracle = CheckEngine(store, max_depth=5)
        reqs = _workload()
        want = [oracle.subject_is_allowed(r) for r in reqs]
        got = [None] * len(reqs)
        errs = []

        def worker(wid, n_threads=6):
            try:
                # staggered slices -> batches coalesce at varying sizes,
                # landing in different padding buckets concurrently
                for i in range(wid, len(reqs), n_threads):
                    got[i] = pipelined.check(reqs[i], timeout=30)
            except Exception as e:  # pragma: no cover - failure detail
                errs.append(e)

        threads = [
            threading.Thread(target=worker, args=(w,)) for w in range(6)
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=60)
        assert not errs
        assert got == want

    def test_encoded_cache_hits_and_stays_correct(self, store, pipelined):
        oracle = CheckEngine(store, max_depth=5)
        reqs = _workload()[:32]
        want = [oracle.subject_is_allowed(r) for r in reqs]
        # two passes: the second round's rows resolve from the
        # encoded-request cache, and must still match the oracle
        for _round in range(2):
            assert [pipelined.check(r, timeout=30) for r in reqs] == want
        assert len(pipelined.encoded_cache) > 0

    def test_write_invalidates_encoded_cache(self, store, pipelined):
        req = t("n:doc0#view@newcomer")
        assert pipelined.check(req, timeout=30) is False
        store.write_relation_tuples(t("n:group0#member@newcomer"))
        # the snapshot version moved: the cached denial must not be served
        assert pipelined.check(req, timeout=30) is True

    def test_pipeline_stats_shape(self, pipelined):
        stats = pipelined.pipeline_stats()
        assert stats["pipelined"] is True
        assert stats["pipeline_depth"] == 2
        assert stats["encode_workers"] == 2
        for key in (
            "queue_depth",
            "launch_queue_depth",
            "decode_queue_depth",
            "batches_in_pipeline",
            "encoded_cache_entries",
        ):
            assert isinstance(stats[key], int)


class TestCheckBatchBulkCache:
    def test_check_batch_uses_result_cache(self, store):
        mgr = SnapshotManager(store)
        engine = DeviceCheckEngine(mgr, max_depth=5)
        calls = []
        real = engine.batch_check

        def counting(requests, max_depth=0, depths=None):
            calls.append(len(requests))
            return real(requests, max_depth, depths=depths)

        engine.batch_check = counting
        b = CheckBatcher(
            engine,
            window_s=0,
            cache=CheckResultCache(1024),
            version_fn=lambda: store.version,
        )
        try:
            oracle = CheckEngine(store, max_depth=5)
            reqs = _workload()[:20]
            want = [oracle.subject_is_allowed(r) for r in reqs]
            cold = b.check_batch(reqs)
            n_cold = sum(calls)
            hot = b.check_batch(reqs)
            assert cold == want and hot == want
            # the hot batch was answered from the bulk cache: no new
            # engine dispatches
            assert sum(calls) == n_cold
            # a partial miss dispatches ONLY the missing rows
            mixed = reqs[:10] + [t("n:docnew#view@alice0")]
            b.check_batch(mixed)
            assert sum(calls) == n_cold + 1 and calls[-1] == 1
        finally:
            b.close()

    def test_serial_fallback_for_engines_without_split_api(self, store):
        # host oracle has no encode/launch/decode: pipeline_depth is
        # silently ignored and the serial dispatcher serves correctly
        b = CheckBatcher(
            CheckEngine(store, max_depth=5), window_s=0, pipeline_depth=2
        )
        try:
            assert b.pipelined is False
            assert b.check(t("n:doc0#view@direct0"), timeout=30) is True
        finally:
            b.close()
