"""Observability e2e: structured logs, tracing spans, /metrics exposition,
gRPC interceptors, and server reflection against a live two-plane server
(reference wires these in registry_default.go:118-136, 276, 289-291,
337-401; this is the keto_tpu equivalent)."""

import json
import logging

import grpc
import httpx
import pytest

from keto_tpu.api import acl_pb2, check_service_pb2, reflection_pb2
from keto_tpu.api.services import CheckServiceStub
from keto_tpu.driver import Config
from keto_tpu.telemetry import MetricsRegistry, Tracer, get_logger
from keto_tpu.telemetry.logging import configure_logging
from tests.test_api_server import ServerFixture


@pytest.fixture(scope="module")
def server():
    cfg = Config(
        values={
            "namespaces": [{"id": 1, "name": "videos"}],
            "serve": {
                "read": {"port": 0, "host": "127.0.0.1"},
                "write": {"port": 0, "host": "127.0.0.1"},
            },
            "log": {"level": "debug", "format": "json"},
            "tracing": {"provider": "log"},
        },
        env={},
    )
    s = ServerFixture(cfg)
    yield s
    s.stop()


def _check(server, allowed_subject="cat lady"):
    with grpc.insecure_channel(
        f"127.0.0.1:{server.read_port}"
    ) as ch:
        return CheckServiceStub(ch).Check(
            check_service_pb2.CheckRequest(
                namespace="videos",
                object="/cats",
                relation="view",
                subject=acl_pb2.Subject(id=allowed_subject),
            )
        )


class TestMetricsEndpoint:
    def test_metrics_exposed_on_both_planes(self, server):
        # drive one REST check + one gRPC check so counters move
        r = httpx.get(
            f"http://127.0.0.1:{server.read_port}/check",
            params={
                "namespace": "videos",
                "object": "x",
                "relation": "r",
                "subject_id": "nobody",
            },
        )
        assert r.status_code == 403
        _check(server)

        body = httpx.get(
            f"http://127.0.0.1:{server.read_port}/metrics"
        ).text
        assert "# TYPE keto_http_requests_total counter" in body
        assert 'plane="read"' in body
        assert "keto_grpc_requests_total" in body
        assert "keto_checks_total" in body
        assert "keto_store_version" in body
        assert "keto_check_staleness_versions" in body
        # histograms expose cumulative buckets
        assert "keto_http_request_duration_seconds_bucket" in body

        wbody = httpx.get(
            f"http://127.0.0.1:{server.write_port}/metrics"
        ).text
        assert "keto_store_tuples" in wbody

    def test_request_metrics_label_route_not_path(self, server):
        body = httpx.get(
            f"http://127.0.0.1:{server.read_port}/metrics"
        ).text
        assert 'route="/check"' in body
        # raw object paths must never become label values
        assert 'route="/check?namespace' not in body


class TestStructuredLogs:
    def test_request_logs_emitted(self, server, capfd):
        import time

        _check(server)
        httpx.get(f"http://127.0.0.1:{server.read_port}/version")

        # server-side logs land a beat after the client's call returns
        # (the handler's finally runs concurrently with response delivery)
        def collect(pred, timeout=5.0):
            lines = []
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                err = capfd.readouterr().err
                for line in err.splitlines():
                    if line.startswith("{"):
                        lines.append(json.loads(line))
                if pred(lines):
                    return lines
                time.sleep(0.05)
            return lines

        def done(lines):
            msgs = {l.get("msg") for l in lines}
            return {"grpc", "http", "span"} <= msgs

        lines = collect(done)
        grpc_logs = [l for l in lines if l.get("msg") == "grpc"]
        http_logs = [l for l in lines if l.get("msg") == "http"]
        assert any(
            l["method"].endswith("CheckService/Check") and l["code"] == "OK"
            for l in grpc_logs
        )
        assert any(l["route"] == "/version" for l in http_logs)
        # engine spans ride the same structured log (tracing.provider: log)
        span_logs = [l for l in lines if l.get("msg") == "span"]
        assert any(l["span"] == "grpc.request" for l in span_logs)


class TestTracing:
    def test_engine_phase_spans_recorded(self, server):
        _check(server)
        tracer = server.registry.tracer()
        names = {s.name for s in tracer.finished()}
        assert "closure.build" in names
        assert "grpc.request" in names
        build = tracer.finished("closure.build")[-1]
        assert build.duration is not None
        assert "interior" in build.attrs and "kind" in build.attrs

    def test_span_parentage(self):
        tracer = Tracer()
        with tracer.span("outer") as outer:
            with tracer.span("inner"):
                pass
        inner = tracer.finished("inner")[0]
        assert inner.parent_id == outer.span_id
        assert inner.trace_id == outer.trace_id


class TestReflection:
    def test_list_services(self, server):
        with grpc.insecure_channel(
            f"127.0.0.1:{server.read_port}"
        ) as ch:
            stream = ch.stream_stream(
                "/grpc.reflection.v1alpha.ServerReflection/ServerReflectionInfo",
                request_serializer=(
                    reflection_pb2.ServerReflectionRequest.SerializeToString
                ),
                response_deserializer=(
                    reflection_pb2.ServerReflectionResponse.FromString
                ),
            )
            resp = list(
                stream(
                    iter(
                        [
                            reflection_pb2.ServerReflectionRequest(
                                list_services=""
                            )
                        ]
                    )
                )
            )[0]
        names = {
            s.name for s in resp.list_services_response.service
        }
        assert "ory.keto.acl.v1alpha1.CheckService" in names
        assert "grpc.health.v1.Health" in names
        assert "grpc.reflection.v1alpha.ServerReflection" in names

    def test_file_containing_symbol_returns_closure(self, server):
        with grpc.insecure_channel(
            f"127.0.0.1:{server.read_port}"
        ) as ch:
            stream = ch.stream_stream(
                "/grpc.reflection.v1alpha.ServerReflection/ServerReflectionInfo",
                request_serializer=(
                    reflection_pb2.ServerReflectionRequest.SerializeToString
                ),
                response_deserializer=(
                    reflection_pb2.ServerReflectionResponse.FromString
                ),
            )
            reqs = [
                reflection_pb2.ServerReflectionRequest(
                    file_containing_symbol="ory.keto.acl.v1alpha1.CheckService"
                ),
                reflection_pb2.ServerReflectionRequest(
                    file_containing_symbol="no.such.Symbol"
                ),
            ]
            resps = list(stream(iter(reqs)))
        ok, missing = resps
        files = ok.file_descriptor_response.file_descriptor_proto
        assert len(files) >= 2  # check_service.proto + its acl.proto dep
        assert missing.WhichOneof("message_response") == "error_response"


class TestMetricsPrimitives:
    def test_histogram_percentile_and_expose(self):
        m = MetricsRegistry()
        h = m.histogram("x_seconds", "test", buckets=(0.01, 0.1, 1.0))
        for v in (0.005, 0.05, 0.05, 0.5):
            h.observe(v)
        assert h.count == 4
        assert h.percentile(0.5) == 0.1
        text = m.expose()
        assert 'x_seconds_bucket{le="+Inf"} 4' in text
        assert "x_seconds_count 4" in text

    def test_labeled_counter_series(self):
        m = MetricsRegistry()
        c = m.counter("reqs_total", "test", labelnames=("code",))
        c.labels(code="200").inc()
        c.labels(code="200").inc()
        c.labels(code="500").inc()
        text = m.expose()
        assert 'reqs_total{code="200"} 2' in text
        assert 'reqs_total{code="500"} 1' in text

    def test_json_log_fields(self, capfd):
        configure_logging(level="debug", format="json")
        get_logger("t").info("hello", a=1, b="x")
        err = capfd.readouterr().err
        doc = json.loads(err.strip().splitlines()[-1])
        assert doc["msg"] == "hello" and doc["a"] == 1 and doc["b"] == "x"
        # restore default so later tests aren't json-formatted
        configure_logging(level="info", format="text")


class TestOtlpExport:
    """tracing.provider=otlp ships OTLP/HTTP JSON batches to a collector
    (the reference wires opentracing to a real collector end-to-end,
    registry_default.go:118-129 + docker-compose-tracing.yml; here a
    local fake collector receives the standard encoding)."""

    def test_spans_land_in_local_collector(self):
        import json
        import threading
        from http.server import BaseHTTPRequestHandler, HTTPServer

        from keto_tpu.telemetry.tracing import Tracer

        received = []
        got_one = threading.Event()

        class Collector(BaseHTTPRequestHandler):
            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                doc = json.loads(self.rfile.read(n))
                received.append((self.path, doc))
                got_one.set()
                self.send_response(200)
                self.end_headers()
                self.wfile.write(b"{}")

            def log_message(self, *a):
                pass

        httpd = HTTPServer(("127.0.0.1", 0), Collector)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        tracer = Tracer(
            provider="otlp",
            otlp_endpoint=f"http://127.0.0.1:{httpd.server_port}",
            service_name="keto-test",
            flush_interval_s=0.1,
        )
        try:
            with tracer.span("parent", kind="outer") as parent:
                with tracer.span("child", edges=42):
                    pass
            tracer.flush(10)
            assert got_one.wait(10)
            path, doc = received[0]
            assert path == "/v1/traces"
            rs = doc["resourceSpans"][0]
            svc = {
                a["key"]: a["value"]["stringValue"]
                for a in rs["resource"]["attributes"]
            }
            assert svc["service.name"] == "keto-test"
            spans = {
                s["name"]: s for s in rs["scopeSpans"][0]["spans"]
            }
            assert set(spans) == {"parent", "child"}
            child = spans["child"]
            assert child["parentSpanId"] == spans["parent"]["spanId"]
            assert child["traceId"] == spans["parent"]["traceId"]
            attrs = {
                a["key"]: a["value"]["stringValue"]
                for a in child["attributes"]
            }
            assert attrs["edges"] == "42"
            assert int(child["endTimeUnixNano"]) >= int(
                child["startTimeUnixNano"]
            )
        finally:
            tracer.close()
            httpd.shutdown()

    def test_collector_outage_never_blocks_spans(self):
        from keto_tpu.telemetry.tracing import Tracer

        tracer = Tracer(
            provider="otlp",
            otlp_endpoint="http://127.0.0.1:1",  # nothing listens
            flush_interval_s=0.05,
        )
        try:
            for _ in range(50):
                with tracer.span("work"):
                    pass
            tracer.flush(10)  # must return despite the dead endpoint
            assert len(tracer.finished("work")) == 50
        finally:
            tracer.close()
