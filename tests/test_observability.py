"""Observability e2e: structured logs, tracing spans, /metrics exposition,
gRPC interceptors, and server reflection against a live two-plane server
(reference wires these in registry_default.go:118-136, 276, 289-291,
337-401; this is the keto_tpu equivalent)."""

import json
import logging

import grpc
import httpx
import pytest

from keto_tpu.api import acl_pb2, check_service_pb2, reflection_pb2
from keto_tpu.api.services import CheckServiceStub
from keto_tpu.driver import Config
from keto_tpu.telemetry import MetricsRegistry, Tracer, get_logger
from keto_tpu.telemetry.logging import configure_logging
from tests.test_api_server import ServerFixture


@pytest.fixture(scope="module")
def server():
    cfg = Config(
        values={
            "namespaces": [{"id": 1, "name": "videos"}],
            "serve": {
                "read": {"port": 0, "host": "127.0.0.1"},
                "write": {"port": 0, "host": "127.0.0.1"},
            },
            "log": {"level": "debug", "format": "json"},
            "tracing": {"provider": "log"},
        },
        env={},
    )
    s = ServerFixture(cfg)
    yield s
    s.stop()


def _check(server, allowed_subject="cat lady"):
    with grpc.insecure_channel(
        f"127.0.0.1:{server.read_port}"
    ) as ch:
        return CheckServiceStub(ch).Check(
            check_service_pb2.CheckRequest(
                namespace="videos",
                object="/cats",
                relation="view",
                subject=acl_pb2.Subject(id=allowed_subject),
            )
        )


class TestMetricsEndpoint:
    def test_metrics_exposed_on_both_planes(self, server):
        # drive one REST check + one gRPC check so counters move
        r = httpx.get(
            f"http://127.0.0.1:{server.read_port}/check",
            params={
                "namespace": "videos",
                "object": "x",
                "relation": "r",
                "subject_id": "nobody",
            },
        )
        assert r.status_code == 403
        _check(server)

        body = httpx.get(
            f"http://127.0.0.1:{server.read_port}/metrics"
        ).text
        assert "# TYPE keto_http_requests_total counter" in body
        assert 'plane="read"' in body
        assert "keto_grpc_requests_total" in body
        assert "keto_checks_total" in body
        assert "keto_store_version" in body
        assert "keto_check_staleness_versions" in body
        # histograms expose cumulative buckets
        assert "keto_http_request_duration_seconds_bucket" in body

        wbody = httpx.get(
            f"http://127.0.0.1:{server.write_port}/metrics"
        ).text
        assert "keto_store_tuples" in wbody

    def test_request_metrics_label_route_not_path(self, server):
        body = httpx.get(
            f"http://127.0.0.1:{server.read_port}/metrics"
        ).text
        assert 'route="/check"' in body
        # raw object paths must never become label values
        assert 'route="/check?namespace' not in body


class TestStructuredLogs:
    def test_request_logs_emitted(self, server, capfd):
        import time

        _check(server)
        httpx.get(f"http://127.0.0.1:{server.read_port}/version")

        # server-side logs land a beat after the client's call returns
        # (the handler's finally runs concurrently with response delivery)
        def collect(pred, timeout=5.0):
            lines = []
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                err = capfd.readouterr().err
                for line in err.splitlines():
                    if line.startswith("{"):
                        lines.append(json.loads(line))
                if pred(lines):
                    return lines
                time.sleep(0.05)
            return lines

        def done(lines):
            msgs = {l.get("msg") for l in lines}
            return {"grpc", "http", "span"} <= msgs

        lines = collect(done)
        grpc_logs = [l for l in lines if l.get("msg") == "grpc"]
        http_logs = [l for l in lines if l.get("msg") == "http"]
        assert any(
            l["method"].endswith("CheckService/Check") and l["code"] == "OK"
            for l in grpc_logs
        )
        assert any(l["route"] == "/version" for l in http_logs)
        # engine spans ride the same structured log (tracing.provider: log)
        span_logs = [l for l in lines if l.get("msg") == "span"]
        assert any(l["span"] == "grpc.request" for l in span_logs)


class TestTracing:
    def test_engine_phase_spans_recorded(self, server):
        _check(server)
        tracer = server.registry.tracer()
        names = {s.name for s in tracer.finished()}
        assert "closure.build" in names
        assert "grpc.request" in names
        build = tracer.finished("closure.build")[-1]
        assert build.duration is not None
        assert "interior" in build.attrs and "kind" in build.attrs

    def test_span_parentage(self):
        tracer = Tracer()
        with tracer.span("outer") as outer:
            with tracer.span("inner"):
                pass
        inner = tracer.finished("inner")[0]
        assert inner.parent_id == outer.span_id
        assert inner.trace_id == outer.trace_id


class TestReflection:
    def test_list_services(self, server):
        with grpc.insecure_channel(
            f"127.0.0.1:{server.read_port}"
        ) as ch:
            stream = ch.stream_stream(
                "/grpc.reflection.v1alpha.ServerReflection/ServerReflectionInfo",
                request_serializer=(
                    reflection_pb2.ServerReflectionRequest.SerializeToString
                ),
                response_deserializer=(
                    reflection_pb2.ServerReflectionResponse.FromString
                ),
            )
            resp = list(
                stream(
                    iter(
                        [
                            reflection_pb2.ServerReflectionRequest(
                                list_services=""
                            )
                        ]
                    )
                )
            )[0]
        names = {
            s.name for s in resp.list_services_response.service
        }
        assert "ory.keto.acl.v1alpha1.CheckService" in names
        assert "grpc.health.v1.Health" in names
        assert "grpc.reflection.v1alpha.ServerReflection" in names

    def test_file_containing_symbol_returns_closure(self, server):
        with grpc.insecure_channel(
            f"127.0.0.1:{server.read_port}"
        ) as ch:
            stream = ch.stream_stream(
                "/grpc.reflection.v1alpha.ServerReflection/ServerReflectionInfo",
                request_serializer=(
                    reflection_pb2.ServerReflectionRequest.SerializeToString
                ),
                response_deserializer=(
                    reflection_pb2.ServerReflectionResponse.FromString
                ),
            )
            reqs = [
                reflection_pb2.ServerReflectionRequest(
                    file_containing_symbol="ory.keto.acl.v1alpha1.CheckService"
                ),
                reflection_pb2.ServerReflectionRequest(
                    file_containing_symbol="no.such.Symbol"
                ),
            ]
            resps = list(stream(iter(reqs)))
        ok, missing = resps
        files = ok.file_descriptor_response.file_descriptor_proto
        assert len(files) >= 2  # check_service.proto + its acl.proto dep
        assert missing.WhichOneof("message_response") == "error_response"


class TestMetricsPrimitives:
    def test_histogram_percentile_and_expose(self):
        m = MetricsRegistry()
        h = m.histogram("x_seconds", "test", buckets=(0.01, 0.1, 1.0))
        for v in (0.005, 0.05, 0.05, 0.5):
            h.observe(v)
        assert h.count == 4
        assert h.percentile(0.5) == 0.1
        text = m.expose()
        assert 'x_seconds_bucket{le="+Inf"} 4' in text
        assert "x_seconds_count 4" in text

    def test_labeled_counter_series(self):
        m = MetricsRegistry()
        c = m.counter("reqs_total", "test", labelnames=("code",))
        c.labels(code="200").inc()
        c.labels(code="200").inc()
        c.labels(code="500").inc()
        text = m.expose()
        assert 'reqs_total{code="200"} 2' in text
        assert 'reqs_total{code="500"} 1' in text

    def test_json_log_fields(self, capfd):
        configure_logging(level="debug", format="json")
        get_logger("t").info("hello", a=1, b="x")
        err = capfd.readouterr().err
        doc = json.loads(err.strip().splitlines()[-1])
        assert doc["msg"] == "hello" and doc["a"] == 1 and doc["b"] == "x"
        # restore default so later tests aren't json-formatted
        configure_logging(level="info", format="text")


class TestOtlpExport:
    """tracing.provider=otlp ships OTLP/HTTP JSON batches to a collector
    (the reference wires opentracing to a real collector end-to-end,
    registry_default.go:118-129 + docker-compose-tracing.yml; here a
    local fake collector receives the standard encoding)."""

    def test_spans_land_in_local_collector(self):
        import json
        import threading
        from http.server import BaseHTTPRequestHandler, HTTPServer

        from keto_tpu.telemetry.tracing import Tracer

        received = []
        got_one = threading.Event()

        class Collector(BaseHTTPRequestHandler):
            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                doc = json.loads(self.rfile.read(n))
                received.append((self.path, doc))
                got_one.set()
                self.send_response(200)
                self.end_headers()
                self.wfile.write(b"{}")

            def log_message(self, *a):
                pass

        httpd = HTTPServer(("127.0.0.1", 0), Collector)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        tracer = Tracer(
            provider="otlp",
            otlp_endpoint=f"http://127.0.0.1:{httpd.server_port}",
            service_name="keto-test",
            flush_interval_s=0.1,
        )
        try:
            with tracer.span("parent", kind="outer") as parent:
                with tracer.span("child", edges=42):
                    pass
            tracer.flush(10)
            assert got_one.wait(10)
            path, doc = received[0]
            assert path == "/v1/traces"
            rs = doc["resourceSpans"][0]
            svc = {
                a["key"]: a["value"]["stringValue"]
                for a in rs["resource"]["attributes"]
            }
            assert svc["service.name"] == "keto-test"
            spans = {
                s["name"]: s for s in rs["scopeSpans"][0]["spans"]
            }
            assert set(spans) == {"parent", "child"}
            child = spans["child"]
            assert child["parentSpanId"] == spans["parent"]["spanId"]
            assert child["traceId"] == spans["parent"]["traceId"]
            attrs = {
                a["key"]: a["value"]["stringValue"]
                for a in child["attributes"]
            }
            assert attrs["edges"] == "42"
            assert int(child["endTimeUnixNano"]) >= int(
                child["startTimeUnixNano"]
            )
        finally:
            tracer.close()
            httpd.shutdown()

    def test_collector_outage_never_blocks_spans(self):
        from keto_tpu.telemetry.tracing import Tracer

        tracer = Tracer(
            provider="otlp",
            otlp_endpoint="http://127.0.0.1:1",  # nothing listens
            flush_interval_s=0.05,
        )
        try:
            for _ in range(50):
                with tracer.span("work"):
                    pass
            tracer.flush(10)  # must return despite the dead endpoint
            assert len(tracer.finished("work")) == 50
        finally:
            tracer.close()


# -- introspection plane (PR 6): flight recorder, exemplars, SLO, /debug ------


def _lint_module():
    """Load tools/lint_metrics.py as a module (tools/ is not a package)."""
    import importlib.util
    import os

    path = os.path.abspath(
        os.path.join(os.path.dirname(__file__), "..", "tools", "lint_metrics.py")
    )
    spec = importlib.util.spec_from_file_location("lint_metrics", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestLabelEscaping:
    def test_fmt_labels_escapes_newlines_quotes_backslashes(self):
        from keto_tpu.telemetry.metrics import _fmt_labels

        out = _fmt_labels({"msg": 'a\nb"c\\d'})
        assert out == '{msg="a\\nb\\"c\\\\d"}'
        assert "\n" not in out

    def test_newline_label_value_stays_one_exposition_line(self):
        m = MetricsRegistry()
        c = m.counter("esc_total", "t", labelnames=("detail",))
        c.labels(detail="line1\nline2").inc()
        lines = [
            l for l in m.expose().splitlines() if l.startswith("esc_total{")
        ]
        assert len(lines) == 1
        assert '\\n' in lines[0]


class TestExemplars:
    def test_exemplars_only_in_openmetrics_exposition(self):
        m = MetricsRegistry()
        h = m.histogram("ex_seconds", "t", buckets=(0.1, 1.0))
        h.observe(0.05, exemplar={"trace_id": "deadbeef"})
        plain = m.expose()
        om = m.expose(openmetrics=True)
        assert "# {" not in plain
        assert "# EOF" not in plain
        assert '# {trace_id="deadbeef"} 0.05' in om
        assert om.rstrip("\n").endswith("# EOF")

    def test_last_exemplar_per_bucket_wins(self):
        m = MetricsRegistry()
        h = m.histogram("ex2_seconds", "t", buckets=(0.1, 1.0))
        h.observe(0.01, exemplar={"trace_id": "old"})
        h.observe(0.02, exemplar={"trace_id": "new"})
        om = m.expose(openmetrics=True)
        assert 'trace_id="new"' in om
        assert 'trace_id="old"' not in om

    def test_lint_round_trip_both_formats(self):
        lint = _lint_module()
        m = MetricsRegistry()
        c = m.counter("rt_total", "t", labelnames=("k",))
        c.labels(k="v").inc()
        h = m.histogram("rt_seconds", "t", buckets=(0.1, 1.0))
        h.observe(0.05, exemplar={"trace_id": "abc"})
        assert lint.lint_text(m.expose(), openmetrics=False) == []
        assert lint.lint_text(m.expose(openmetrics=True), openmetrics=True) == []
        # an OpenMetrics body presented as plain text must be flagged
        violations = lint.lint_text(m.expose(openmetrics=True), openmetrics=False)
        assert any("exemplar" in v for v in violations)
        assert any("EOF" in v for v in violations)

    def test_lint_catches_broken_families(self):
        lint = _lint_module()
        bad = (
            "# HELP bad_counter c\n"
            "# TYPE bad_counter counter\n"
            "bad_counter 1\n"
            "orphan_metric 2\n"
            'dup{a="1"} 1\n'
        )
        violations = lint.lint_text(bad)
        assert any("_total" in v for v in violations)
        assert any("orphan_metric" in v for v in violations)


class TestFlightRecorder:
    def test_ring_eviction_newest_first(self):
        from keto_tpu.telemetry import FlightRecorder

        fr = FlightRecorder(capacity=3)
        for i in range(5):
            fr.record(idx=i)
        recs = fr.records()
        assert [r["idx"] for r in recs] == [4, 3, 2]
        assert recs[0]["seq"] == 4
        assert fr.total_recorded == 5
        assert fr.records(1)[0]["idx"] == 4
        assert fr.stats()["size"] == 3

    def test_fatal_dump_writes_ring_and_stacks(self, tmp_path):
        from keto_tpu.telemetry import FlightRecorder

        fr = FlightRecorder(
            capacity=8, dump_dir=str(tmp_path), flush_interval_s=60
        )
        try:
            fr.record(trace_id="abc123", outcome="error:Boom")
            fr.install_fatal_dump()
            fr.dump_fatal()
            doc = json.loads((tmp_path / "flight.json").read_text())
            assert doc["records"][0]["trace_id"] == "abc123"
            stacks = (tmp_path / "fatal.stacks").read_text()
            assert "File" in stacks or "Thread" in stacks
        finally:
            fr.close()
        # close() must restore the excepthook and disable faulthandler
        import faulthandler
        import sys

        assert not faulthandler.is_enabled() or True  # other tests may arm it
        assert sys.excepthook is sys.__excepthook__ or fr._prev_excepthook is None


class TestSLOBurnRate:
    def test_burn_rate_math(self):
        from keto_tpu.telemetry import SLOTracker

        clk = [1000.0]
        t = SLOTracker(
            objective=0.9, latency_target_s=0.1,
            fast_window_s=60, slow_window_s=600, clock=lambda: clk[0],
        )
        for _ in range(9):
            assert t.record(0.01) is False
        assert t.record(0.01, error=True) is True
        # 1 bad / 10 total = 10% bad over a 10% budget -> burn exactly 1.0
        assert t.burn_rate(60) == pytest.approx(1.0)
        assert t.budget_remaining() == pytest.approx(0.0)
        # latency above the target is bad even without an error
        assert t.record(0.5) is True

    def test_window_expiry(self):
        from keto_tpu.telemetry import SLOTracker

        clk = [1000.0]
        t = SLOTracker(
            objective=0.9, fast_window_s=60, slow_window_s=600,
            clock=lambda: clk[0],
        )
        t.record(0.01, error=True)
        assert t.burn_rate(600) > 0
        clk[0] += 700  # past the slow window: the bad event ages out
        t.record(0.01)
        assert t.burn_rate(600) == pytest.approx(0.0)

    def test_alert_fires_once_per_cooldown(self):
        from keto_tpu.telemetry import SLOTracker

        warnings = []

        class FakeLog:
            def warning(self, msg, **fields):
                warnings.append((msg, fields))

        clk = [1000.0]
        t = SLOTracker(
            logger=FakeLog(), objective=0.9, alert_burn_rate=1.0,
            alert_cooldown_s=300, fast_window_s=60, slow_window_s=600,
            clock=lambda: clk[0],
        )
        t.record(0.01, error=True)
        assert t.alerts_fired == 1
        assert warnings and warnings[0][0] == "slo_burn_alert"
        assert warnings[0][1]["fast_burn_rate"] >= 1.0
        clk[0] += 10  # within cooldown: no second alert
        t.record(0.01, error=True)
        assert t.alerts_fired == 1
        clk[0] += 300  # past cooldown
        t.record(0.01, error=True)
        assert t.alerts_fired == 2


@pytest.fixture(scope="module")
def drill():
    """A device-engine server with an armed /debug surface and tight
    flight/SLO thresholds — the seeded slow-request drill target."""
    import os
    import tempfile

    dump_dir = tempfile.mkdtemp(prefix="keto-flight-")
    cfg = Config(
        values={
            "namespaces": [{"id": 1, "name": "videos"}],
            "serve": {
                "read": {"port": 0, "host": "127.0.0.1"},
                "write": {"port": 0, "host": "127.0.0.1"},
            },
            "log": {"level": "error"},
            "engine": {"mode": "device", "max_batch": 64},
            "telemetry": {
                "flight": {
                    "capacity": 64, "slow_ms": 50, "dir": dump_dir,
                    "flush_interval_s": 0.2,
                },
                "slo": {
                    "objective": 0.9, "latency_target_ms": 50,
                    "fast_window_s": 60, "slow_window_s": 600,
                    "alert_burn_rate": 0.01, "alert_cooldown_s": 1,
                },
            },
            "debug": {"enabled": True, "token": "hunter2"},
        },
        env={},
    )
    s = ServerFixture(cfg)
    s.dump_dir = dump_dir
    yield s
    s.stop()


def _dbg(server, path, token="hunter2", **kw):
    headers = kw.pop("headers", {})
    if token is not None:
        headers["X-Debug-Token"] = token
    return httpx.get(
        f"http://127.0.0.1:{server.read_port}{path}",
        headers=headers, timeout=30, **kw,
    )


class TestDebugSurface:
    def test_token_gate(self, drill):
        assert _dbg(drill, "/debug/stacks", token=None).status_code == 403
        assert _dbg(drill, "/debug/stacks", token="wrong").status_code == 403
        r = _dbg(drill, "/debug/stacks")
        assert r.status_code == 200
        assert "MainThread" in r.text

    def test_bearer_token_accepted(self, drill):
        r = httpx.get(
            f"http://127.0.0.1:{drill.read_port}/debug/stacks",
            headers={"Authorization": "Bearer hunter2"}, timeout=30,
        )
        assert r.status_code == 200

    def test_config_redacts_secrets(self, drill):
        r = _dbg(drill, "/debug/config")
        assert r.status_code == 200
        assert "hunter2" not in r.text
        assert "[redacted]" in r.text
        doc = r.json()
        assert doc["config"]["debug"]["token"] == "[redacted]"

    def test_graph_panel_endpoint(self, drill):
        doc = _dbg(drill, "/debug/graph").json()
        assert "graph" in doc and "devices" in doc
        assert "tuples" in doc["graph"]

    def test_traces_endpoint(self, drill):
        doc = _dbg(drill, "/debug/traces").json()
        assert isinstance(doc["spans"], list)

    def test_debug_disabled_is_404(self):
        cfg = Config(
            values={
                "namespaces": [{"id": 1, "name": "videos"}],
                "serve": {
                    "read": {"port": 0, "host": "127.0.0.1"},
                    "write": {"port": 0, "host": "127.0.0.1"},
                },
                "log": {"level": "error"},
                "debug": {"enabled": False},
            },
            env={},
        )
        s = ServerFixture(cfg)
        try:
            r = httpx.get(
                f"http://127.0.0.1:{s.read_port}/debug/stacks", timeout=30
            )
            assert r.status_code == 404
            # the rest of the plane still serves
            assert (
                httpx.get(
                    f"http://127.0.0.1:{s.read_port}/health/alive", timeout=30
                ).status_code
                == 200
            )
        finally:
            s.stop()


class TestIntrospectionDrill:
    """The acceptance drill: an armed device.slow fault must leave a
    correlated evidence trail — flight-recorder entry, histogram exemplar
    trace id, and a burning SLO gauge — with no log spelunking."""

    def test_slow_fault_leaves_full_evidence(self, drill):
        from keto_tpu.faults import FAULTS

        base = f"http://127.0.0.1:{drill.read_port}"
        put = httpx.put(
            f"http://127.0.0.1:{drill.write_port}/relation-tuples",
            json={
                "namespace": "videos",
                "object": "/cats",
                "relation": "view",
                "subject_id": "cat lady",
            },
            timeout=60,
        )
        assert put.status_code in (200, 201)
        try:
            FAULTS.arm_slow("device.slow", sleep_ms=120, times=8)
            r = httpx.get(
                f"{base}/check",
                params={
                    "namespace": "videos",
                    "object": "/cats",
                    "relation": "view",
                    "subject_id": "cat lady",
                },
                timeout=60,
            )
            assert r.status_code == 200
        finally:
            FAULTS.reset()

        # 1. the flight recorder captured it (slow >= 50ms threshold)
        doc = _dbg(drill, "/debug/flight").json()
        slow = [
            rec for rec in doc["records"]
            if rec.get("slow") and rec.get("transport") == "rest"
        ]
        assert slow, f"no slow flight record in {doc['records']!r}"
        rec = slow[0]
        assert rec["outcome"] == "ok"
        assert rec["duration_ms"] >= 100
        trace_id = rec["trace_id"]
        assert len(trace_id) == 32

        # 2. the check-latency histogram carries that trace id as an
        #    OpenMetrics exemplar
        om = httpx.get(
            f"{base}/metrics",
            headers={"Accept": "application/openmetrics-text"},
            timeout=30,
        )
        assert "application/openmetrics-text" in om.headers["content-type"]
        assert om.text.rstrip("\n").endswith("# EOF")
        assert "keto_check_duration_seconds_bucket" in om.text
        assert f'trace_id="{trace_id}"' in om.text

        # 3. the SLO burn-rate gauge is non-zero (50ms target, ~120ms hit)
        plain = httpx.get(f"{base}/metrics", timeout=30).text
        burn = [
            l for l in plain.splitlines()
            if l.startswith('keto_slo_burn_rate{window="fast"}')
        ]
        assert burn, "keto_slo_burn_rate{window=fast} not exposed"
        assert float(burn[0].split()[-1]) > 0
        assert "keto_slo_bad_events_total" in plain

        # 4. exemplars never leak into the plain-text exposition
        assert "# {" not in plain

        # 5. the armed dump_dir got the ring flushed to disk
        import time as _time

        deadline = _time.time() + 5
        flight_path = None
        while _time.time() < deadline:
            import os

            p = os.path.join(drill.dump_dir, "flight.json")
            if os.path.exists(p):
                flight_path = p
                break
            _time.sleep(0.1)
        assert flight_path, "flight ring never flushed to dump dir"
        disk = json.loads(open(flight_path).read())
        assert any(r.get("trace_id") == trace_id for r in disk["records"])

    def test_both_expositions_stay_lint_clean(self, drill):
        lint = _lint_module()
        base = f"http://127.0.0.1:{drill.read_port}"
        plain = httpx.get(f"{base}/metrics", timeout=30).text
        om = httpx.get(
            f"{base}/metrics",
            headers={"Accept": "application/openmetrics-text"},
            timeout=30,
        ).text
        assert lint.lint_text(plain, openmetrics=False) == []
        assert lint.lint_text(om, openmetrics=True) == []

    def test_debug_snapshot_tarball(self, drill, tmp_path):
        import tarfile

        from click.testing import CliRunner

        from keto_tpu.cli import cli

        out = str(tmp_path / "snap.tar.gz")
        res = CliRunner().invoke(
            cli,
            [
                "--read-remote", f"127.0.0.1:{drill.read_port}",
                "debug", "snapshot", "--out", out, "--token", "hunter2",
            ],
        )
        assert res.exit_code == 0, res.output
        with tarfile.open(out) as tar:
            names = set(tar.getnames())
            assert {
                "stacks.txt", "config.json", "graph.json",
                "flight.json", "traces.json", "metrics.prom",
            } <= names
            cfg_doc = json.loads(tar.extractfile("config.json").read())
            assert cfg_doc["config"]["debug"]["token"] == "[redacted]"
            stacks = tar.extractfile("stacks.txt").read().decode()
            assert "MainThread" in stacks


class TestBenchHeartbeat:
    def test_heartbeat_appends_jsonl(self, tmp_path, monkeypatch):
        import bench

        hb = tmp_path / "hb.jsonl"
        monkeypatch.setenv("BENCH_HEARTBEAT_FILE", str(hb))
        monkeypatch.setattr(bench, "_LAST_PHASE", None)
        bench._heartbeat("phase-one")
        bench._heartbeat("phase-two", skipped="budget", budget_left_s=1.5)
        lines = [json.loads(l) for l in hb.read_text().splitlines()]
        assert len(lines) == 2
        assert lines[0]["phase"] == "phase-one"
        assert lines[0]["last_completed"] is None
        assert lines[1]["phase"] == "phase-two"
        assert lines[1]["last_completed"] == "phase-one"
        assert lines[1]["skipped"] == "budget"
        for doc in lines:
            assert "wall_s" in doc and "t_mono" in doc and "t" in doc
