"""Id-native wire tier suite: the encoded BatchCheck path end to end.

- wirecodec frame round-trips (request columns, response bitset)
- vocab sync protocol: snapshot paging, delta catch-up, lineage bounce
- client VocabCache: bootstrap/encode parity with the server vocab,
  unknown keys -> -1 -> allowed False
- epoch-mismatch resync drill: a write lands between encode() and the
  request; the server bounces 409/FAILED_PRECONDITION with the typed
  resync hint; sync() + retry succeeds — on BOTH transports
- encoded-vs-columnar parity fuzz through the live REST and gRPC
  transports (same answers as the per-tuple string path)
- per-tenant QoS on the encoded path: the namespace-id column is
  bucketed without string materialization; a drained tenant 429s
- shm ring fault drills: parent death fails pending futures with the
  typed RingError (no lost futures), a dead worker retires only its
  lane, slot exhaustion is a retryable 429, remote errors revive typed
"""

import asyncio
import pickle
import threading
import time

import grpc
import httpx
import numpy as np
import pytest

from keto_tpu.api import wirecodec
from keto_tpu.api.encoded import EncodedCheckFront
from keto_tpu.api.services import _PKG
from keto_tpu.client import GrpcClient, RestClient, VocabCache
from keto_tpu.driver import Config, Registry
from keto_tpu.engine.shmring import (
    RingBackend,
    RingClient,
    RingError,
    RingRemoteError,
    RingServer,
    WireRing,
)
from keto_tpu.graph import SnapshotManager, vocabsync
from keto_tpu.relationtuple import RelationTuple, SubjectID, SubjectSet
from keto_tpu.store import InMemoryTupleStore
from keto_tpu.utils.errors import (
    DeadlineExceeded,
    ErrResourceExhausted,
    ErrVocabEpochMismatch,
)


def _t(s: str) -> RelationTuple:
    return RelationTuple.from_string(s)


# ---------------------------------------------------------------------------
# wirecodec
# ---------------------------------------------------------------------------


class TestWirecodec:
    def test_request_roundtrip_minimal(self):
        s = np.array([1, 2, 3], dtype=np.int32)
        t = np.array([7, 8, 9], dtype=np.int32)
        frame = wirecodec.encode_check_request(
            s, t, lineage="abcd" * 4, epoch=42
        )
        req = wirecodec.decode_check_request(frame)
        assert req.lineage == "abcd" * 4
        assert req.epoch == 42
        assert req.min_version == 0
        assert req.ns is None
        assert req.depths is None
        assert req.traceparent is None
        np.testing.assert_array_equal(req.start, s)
        np.testing.assert_array_equal(req.target, t)

    def test_request_roundtrip_full(self):
        rng = np.random.default_rng(3)
        n = 257
        s = rng.integers(0, 1 << 20, n).astype(np.int32)
        t = rng.integers(0, 1 << 20, n).astype(np.int32)
        ns = rng.integers(-1, 9, n).astype(np.int32)
        depths = rng.integers(1, 6, n).astype(np.int32)
        tp = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"
        frame = wirecodec.encode_check_request(
            s,
            t,
            lineage="0123456789abcdef",
            epoch=999_999,
            ns=ns,
            depths=depths,
            min_version=17,
            traceparent=tp,
        )
        req = wirecodec.decode_check_request(frame)
        assert req.min_version == 17
        assert req.traceparent == tp
        np.testing.assert_array_equal(req.start, s)
        np.testing.assert_array_equal(req.target, t)
        np.testing.assert_array_equal(req.ns, ns)
        np.testing.assert_array_equal(req.depths, depths)

    def test_response_bitset_roundtrip(self):
        for n in (0, 1, 7, 8, 9, 64, 1000):
            allowed = (np.arange(n) % 3 == 0)
            frame = wirecodec.encode_check_response(allowed, "z42")
            got, tok = wirecodec.decode_check_response(frame)
            assert tok == "z42"
            np.testing.assert_array_equal(
                np.asarray(got, dtype=bool), allowed
            )

    def test_garbage_frames_rejected(self):
        from keto_tpu.utils.errors import ErrMalformedInput

        for bad in (b"", b"nope", b"KTE1" + b"\x00" * 3):
            with pytest.raises(ErrMalformedInput):
                wirecodec.decode_check_request(bad)


# ---------------------------------------------------------------------------
# vocab sync protocol (engine-level, no server)
# ---------------------------------------------------------------------------


class TestVocabSync:
    def _manager(self):
        store = InMemoryTupleStore()
        store.write_relation_tuples(
            _t("n:doc0#view@(n:team0#member)"),
            _t("n:team0#member@alice"),
            _t("m:doc1#view@bob"),
        )
        return store, SnapshotManager(store)

    def test_snapshot_page_and_delta_page(self):
        store, mgr = self._manager()
        vocab = mgr.snapshot().vocab
        lineage = vocabsync.lineage_of(vocab)
        epoch = vocabsync.epoch_of(vocab)
        assert epoch == len(vocab)
        page = vocabsync.snapshot_page(vocab, 0, 10_000)
        assert page["lineage"] == lineage
        assert page["epoch"] == epoch
        assert len(page["keys"]) == epoch
        # delta from the current epoch is empty
        d = vocabsync.delta_page(vocab, lineage, epoch)
        assert d["keys"] == []
        # a write interns new keys; the delta covers exactly them
        store.write_relation_tuples(_t("n:doc9#view@carol"))
        vocab2 = mgr.snapshot().vocab
        d2 = vocabsync.delta_page(vocab2, lineage, epoch)
        assert vocabsync.epoch_of(vocab2) == epoch + len(d2["keys"])
        assert len(d2["keys"]) > 0

    def test_delta_wrong_lineage_raises_typed(self):
        _, mgr = self._manager()
        vocab = mgr.snapshot().vocab
        with pytest.raises(ErrVocabEpochMismatch) as ei:
            vocabsync.delta_page(vocab, "not-the-lineage", 0)
        details = ei.value.envelope()["error"]["details"]
        assert details["reason"] == "vocab_epoch_mismatch"
        assert details["resync"]

    def test_validate_epoch_strictness(self):
        _, mgr = self._manager()
        vocab = mgr.snapshot().vocab
        lineage = vocabsync.lineage_of(vocab)
        epoch = vocabsync.epoch_of(vocab)
        vocabsync.validate_epoch(vocab, lineage, epoch)  # exact: ok
        with pytest.raises(ErrVocabEpochMismatch):
            vocabsync.validate_epoch(vocab, lineage, epoch - 1)
        with pytest.raises(ErrVocabEpochMismatch):
            vocabsync.validate_epoch(vocab, "ffff", epoch)

    def test_ns_table_first_appearance_order(self):
        _, mgr = self._manager()
        vocab = mgr.snapshot().vocab
        table = vocabsync.ns_table_of(vocab)
        # derived by first appearance over 3-tuple keys in id order —
        # deterministic, so an independent derivation agrees
        ids = {table.id_of(name) for name in table.names}
        assert ids == set(range(len(table)))
        assert table.id_of("no-such-ns") == vocabsync.NS_UNKNOWN


# ---------------------------------------------------------------------------
# live server: encoded transports, resync drill, parity fuzz
# ---------------------------------------------------------------------------


class _ServerFixture:
    def __init__(self, config: Config):
        self.registry = Registry(config)
        self.loop = asyncio.new_event_loop()
        self.thread = threading.Thread(
            target=self.loop.run_forever, daemon=True
        )
        self.thread.start()
        fut = asyncio.run_coroutine_threadsafe(
            self.registry.start_all(), self.loop
        )
        self.read_port, self.write_port = fut.result(timeout=180)
        self.http_port = self.registry.read_plane().http_port
        self.grpc_port = self.registry.read_plane().grpc_port

    def stop(self):
        asyncio.run_coroutine_threadsafe(
            self.registry.stop_all(), self.loop
        ).result(timeout=15)
        self.loop.call_soon_threadsafe(self.loop.stop)
        self.thread.join(timeout=5)


_SEED_TUPLES = (
    "n:doc0#view@(n:team0#member)",
    "n:team0#member@alice",
    "n:doc1#view@bob",
    "m:page0#view@carol",
)


@pytest.fixture(scope="module")
def server():
    cfg = Config(
        values={
            "namespaces": [
                {"id": 1, "name": "n"},
                {"id": 2, "name": "m"},
            ],
            "log": {"level": "error"},
            "serve": {
                "read": {"port": 0, "host": "127.0.0.1"},
                "write": {"port": 0, "host": "127.0.0.1"},
            },
        }
    )
    s = _ServerFixture(cfg)
    s.registry.store().write_relation_tuples(
        *[_t(x) for x in _SEED_TUPLES]
    )
    yield s
    s.stop()


def _fresh_reqs(i: int):
    """A batch with hits, misses, and a subject-set row."""
    return [
        _t("n:doc0#view@alice"),
        _t("n:doc0#view@bob"),
        _t("n:doc1#view@bob"),
        _t("m:page0#view@carol"),
        _t(f"n:doc{i}#view@nobody-{i}"),
        _t("n:doc0#view@(n:team0#member)"),
    ]


class TestLiveEncoded:
    def test_vocab_endpoints_page_and_sync(self, server):
        base = f"http://127.0.0.1:{server.read_port}"
        with httpx.Client(base_url=base, timeout=30) as c:
            first = c.get(
                "/vocab/snapshot", params={"offset": 0, "limit": 2}
            ).json()
            assert first["lineage"] and first["epoch"] > 2
            assert len(first["keys"]) == 2
            # paging covers the whole epoch
            total, offset = len(first["keys"]), 2
            while offset < first["epoch"]:
                page = c.get(
                    "/vocab/snapshot",
                    params={"offset": offset, "limit": 1000},
                ).json()
                total += len(page["keys"])
                offset += len(page["keys"])
            assert total == first["epoch"]
            # delta endpoint: wrong lineage is the typed 409
            r = c.get(
                "/vocab/deltas",
                params={"lineage": "beef" * 4, "from": 0},
            )
            assert r.status_code == 409
            details = r.json()["error"]["details"]
            assert details["reason"] == "vocab_epoch_mismatch"
            assert "snapshot" in details["resync"]

    def test_cache_bootstrap_matches_server_vocab(self, server):
        with VocabCache(
            f"http://127.0.0.1:{server.read_port}", page_size=3
        ) as cache:
            cache.bootstrap()
            vocab = server.registry.snapshots().snapshot().vocab
            assert cache.lineage == vocabsync.lineage_of(vocab)
            assert cache.epoch == vocabsync.epoch_of(vocab)
            s_ids, t_ids, ns_ids = cache.encode(
                [_t("n:doc0#view@alice"), _t("zzz:q#r@nobody")]
            )
            # known rows resolve to the server's ids; unknown to -1
            assert s_ids[0] == vocab.lookup(("n", "doc0", "view"))
            assert t_ids[0] == vocab.lookup(("alice",))
            assert s_ids[1] == -1 and t_ids[1] == -1
            table = vocabsync.ns_table_of(vocab)
            assert ns_ids[0] == table.id_of("n")
            assert ns_ids[1] == vocabsync.NS_UNKNOWN

    def test_rest_and_grpc_encoded_parity_fuzz(self, server):
        rest = RestClient(
            f"http://127.0.0.1:{server.http_port}",
            f"http://127.0.0.1:{server.write_port}",
        )
        gc = GrpcClient(
            f"127.0.0.1:{server.grpc_port}",
            f"127.0.0.1:{server.write_port}",
        )
        try:
            cache = rest.vocab_cache()
            cache.bootstrap()
            for i in range(4):
                reqs = _fresh_reqs(i)
                want = rest.batch_check(reqs)
                got_rest = rest.batch_check_encoded(cache, reqs)
                got_grpc = gc.batch_check_encoded(cache, reqs)
                assert got_rest == want, f"REST round {i}"
                assert [bool(v) for v in got_grpc] == want, (
                    f"gRPC round {i}"
                )
        finally:
            rest.close()
            gc.close()

    def test_stale_epoch_bounced_then_resynced_rest(self, server):
        base = f"http://127.0.0.1:{server.read_port}"
        store = server.registry.store()
        with VocabCache(base) as cache:
            cache.bootstrap()
            reqs = [_t("n:doc0#view@alice"), _t("n:fresh0#view@dave")]
            s_ids, t_ids, ns_ids = cache.encode(reqs)
            stale_frame = wirecodec.encode_check_request(
                s_ids,
                t_ids,
                lineage=cache.lineage,
                epoch=cache.epoch,
                ns=ns_ids,
            )
            # the drill: a write lands between encode() and the request
            store.write_relation_tuples(_t("n:fresh0#view@dave"))
            with httpx.Client(base_url=base, timeout=30) as c:
                r = c.post(
                    "/check/batch-encoded",
                    content=stale_frame,
                    headers={
                        "Content-Type": "application/octet-stream"
                    },
                )
                assert r.status_code == 409
                details = r.json()["error"]["details"]
                assert details["reason"] == "vocab_epoch_mismatch"
                assert details["server_epoch"] > details["client_epoch"]
            # sync() follows the delta feed; the re-encoded request now
            # resolves the fresh keys and succeeds
            cache.sync()
            vocab = server.registry.snapshots().snapshot().vocab
            assert cache.epoch == vocabsync.epoch_of(vocab)
            with RestClient(
                f"http://127.0.0.1:{server.http_port}",
                f"http://127.0.0.1:{server.write_port}",
            ) as rest:
                assert rest.batch_check_encoded(cache, reqs) == [
                    True,
                    True,
                ]

    def test_stale_epoch_client_resyncs_transparently_grpc(self, server):
        gc = GrpcClient(
            f"127.0.0.1:{server.grpc_port}",
            f"127.0.0.1:{server.write_port}",
        )
        try:
            with VocabCache(
                f"http://127.0.0.1:{server.read_port}"
            ) as cache:
                cache.bootstrap()
                reqs = [
                    _t("n:doc0#view@alice"),
                    _t("n:fresh1#view@erin"),
                ]
                # the cache is now stale: this write interns new keys
                server.registry.store().write_relation_tuples(
                    _t("n:fresh1#view@erin")
                )
                got = gc.batch_check_encoded(cache, reqs)
                assert [bool(v) for v in got] == [True, True]
        finally:
            gc.close()

    def test_raw_grpc_stale_epoch_is_failed_precondition(self, server):
        with VocabCache(
            f"http://127.0.0.1:{server.read_port}"
        ) as cache:
            cache.bootstrap()
            frame = wirecodec.encode_check_request(
                np.array([0], dtype=np.int32),
                np.array([1], dtype=np.int32),
                lineage=cache.lineage,
                epoch=cache.epoch + 5,  # from the future: never valid
            )
        with grpc.insecure_channel(
            f"127.0.0.1:{server.grpc_port}"
        ) as ch:
            rpc = ch.unary_unary(
                f"/{_PKG}.CheckService/BatchCheckEncoded"
            )
            with pytest.raises(grpc.RpcError) as ei:
                rpc(frame)
            assert (
                ei.value.code() == grpc.StatusCode.FAILED_PRECONDITION
            )
            details = dict(ei.value.trailing_metadata() or ())
            assert "keto-error-details" in details

    def test_attribution_covers_encoded_path(self, server):
        """Flight/attribution rides the encoded transports: coverage
        stays high and the encode stage is ~0 (ids came pre-encoded)."""
        rest = RestClient(
            f"http://127.0.0.1:{server.http_port}",
            f"http://127.0.0.1:{server.write_port}",
        )
        try:
            cache = rest.vocab_cache()
            cache.bootstrap()
            for i in range(10):
                rest.batch_check_encoded(cache, _fresh_reqs(i % 3))
        finally:
            rest.close()
        with httpx.Client(timeout=30) as c:
            debug = c.get(
                f"http://127.0.0.1:{server.http_port}"
                "/debug/attribution"
            ).json()["attribution"]
            flights = c.get(
                f"http://127.0.0.1:{server.http_port}"
                "/debug/flight?n=10"
            ).json()
        assert debug["requests"] >= 10
        assert debug["coverage"] >= 0.95
        stages = debug.get("stages") or {}
        encode_s = (stages.get("encode") or {}).get("seconds", 0.0)
        assert encode_s < 0.05, "encoded path must not pay encode time"
        recs = flights.get("flights") or flights.get("records") or []
        assert any(
            r.get("transport") == "rest-encoded" for r in recs
        ), recs


# ---------------------------------------------------------------------------
# QoS on the encoded path (no strings on the wire)
# ---------------------------------------------------------------------------


class TestEncodedQos:
    def test_ns_counts_from_id_column(self):
        store = InMemoryTupleStore()
        store.write_relation_tuples(
            _t("a:o#r@u1"), _t("b:o#r@u1"), _t("b:o2#r@u2")
        )
        mgr = SnapshotManager(store)
        vocab = mgr.snapshot().vocab
        table = vocabsync.ns_table_of(vocab)
        ids = np.array(
            [table.id_of("a"), table.id_of("b"), table.id_of("b"), -1],
            dtype=np.int32,
        )
        counts = EncodedCheckFront.ns_counts(vocab, ids)
        assert counts["a"] == 1
        assert counts["b"] == 2
        assert counts[vocabsync.NS_UNKNOWN_LABEL] == 1
        assert EncodedCheckFront.ns_counts(vocab, None) is None

    def test_encoded_batch_throttled_per_tenant(self):
        """A drained tenant 429s on the encoded path and the throttle
        counter names it — all derived from the id column."""
        from keto_tpu.engine.batcher import CheckBatcher
        from keto_tpu.engine.closure import ClosureCheckEngine
        from keto_tpu.engine.qos import NamespaceQos, QosThrottled

        store = InMemoryTupleStore()
        store.write_relation_tuples(
            _t("hot:o#r@u1"), _t("cold:o#r@u1")
        )
        mgr = SnapshotManager(store)
        qos = NamespaceQos(
            rate=0.001, burst=4.0
        )  # ~4 rows, then drained
        batcher = CheckBatcher(ClosureCheckEngine(mgr), qos=qos)
        try:
            front = EncodedCheckFront(mgr, batcher)
            vocab = mgr.snapshot().vocab
            table = vocabsync.ns_table_of(vocab)
            hot = table.id_of("hot")
            lineage = vocabsync.lineage_of(vocab)
            epoch = vocabsync.epoch_of(vocab)

            def frame(n):
                return wirecodec.decode_check_request(
                    wirecodec.encode_check_request(
                        np.zeros(n, dtype=np.int32),
                        np.ones(n, dtype=np.int32),
                        lineage=lineage,
                        epoch=epoch,
                        ns=np.full(n, hot, dtype=np.int32),
                    )
                )

            front.check(frame(4))  # burst admits
            with pytest.raises(QosThrottled) as ei:
                front.check(frame(4))
            assert ei.value.namespace == "hot"
            assert ei.value.status_code == 429
            assert qos.stats()["throttled"].get("hot", 0) >= 1
        finally:
            batcher.close()


# ---------------------------------------------------------------------------
# shm ring fault drills
# ---------------------------------------------------------------------------


def _echo_handler(frame: bytes) -> bytes:
    return b"echo:" + frame


class TestWireRing:
    def test_roundtrip_and_remote_stages(self):
        ring = WireRing(2, slots_per_endpoint=2, slot_bytes=4096)
        server = RingServer(ring, _echo_handler)
        server.start()
        clients = [
            RingClient(ring, ring.endpoints[0]),
            RingClient(ring, ring.endpoints[1]),
        ]
        try:
            for i, cl in enumerate(clients):
                payload = cl.submit(f"frame-{i}".encode(), timeout=10)
                kind, body, stages = pickle.loads(payload)
                assert kind == "ok"
                assert body == f"echo:frame-{i}".encode()
                assert isinstance(stages, dict)
        finally:
            for cl in clients:
                cl.close()
            server.stop()
            ring.close()

    def test_remote_error_revives_typed(self):
        def boom(frame):
            raise ErrResourceExhausted("device is saturated")

        ring = WireRing(1, slot_bytes=4096)
        server = RingServer(ring, boom)
        server.start()
        cl = RingClient(ring, ring.endpoints[0])
        try:
            payload = cl.submit(b"x", timeout=10)
            kind, shipped, _ = pickle.loads(payload)
            assert kind == "err"
            err = RingRemoteError(shipped)
            assert err.status_code == 429
            assert err.grpc_code == "RESOURCE_EXHAUSTED"
            assert "saturated" in str(err)
        finally:
            cl.close()
            server.stop()
            ring.close()

    def test_parent_death_fails_pending_futures_typed(self):
        """Worker die-mid-batch drill, seen from the worker: the parent
        vanishes while a request is in flight. Every pending future must
        fail with the typed RingError — no lost futures."""
        hold = threading.Event()

        def stuck(frame):
            hold.wait(10)
            return b"late"

        ring = WireRing(1, slots_per_endpoint=2, slot_bytes=4096)
        server = RingServer(ring, stuck)
        server.start()
        cl = RingClient(ring, ring.endpoints[0])
        errs = []

        def call():
            try:
                cl.submit(b"x", timeout=30)
            except BaseException as e:
                errs.append(e)

        threads = [
            threading.Thread(target=call, daemon=True) for _ in range(2)
        ]
        try:
            for t in threads:
                t.start()
            time.sleep(0.2)
            # parent dies: its doorbell ends close under the stuck handler
            for ep in ring.endpoints:
                ep.parent_sock.close()
            for t in threads:
                t.join(timeout=10)
            assert len(errs) == 2
            assert all(isinstance(e, RingError) for e in errs), errs
            assert all(e.status_code == 503 for e in errs)
            with pytest.raises(RingError):
                cl.submit(b"y", timeout=1)  # broken ring stays typed
        finally:
            hold.set()
            cl.close()
            server._stopping = True
            ring.close()

    def test_dead_worker_retires_only_its_lane(self):
        ring = WireRing(2, slot_bytes=4096)
        server = RingServer(ring, _echo_handler)
        server.start()
        cl0 = RingClient(ring, ring.endpoints[0])
        cl1 = RingClient(ring, ring.endpoints[1])
        try:
            cl0.submit(b"a", timeout=10)
            cl1.close()  # worker 1 dies
            time.sleep(0.2)
            # worker 0's lane keeps serving
            payload = cl0.submit(b"b", timeout=10)
            assert pickle.loads(payload)[0] == "ok"
        finally:
            cl0.close()
            server.stop()
            ring.close()

    def test_slot_exhaustion_is_retryable_429(self):
        hold = threading.Event()

        def stuck(frame):
            hold.wait(10)
            return b"done"

        ring = WireRing(1, slots_per_endpoint=1, slot_bytes=4096)
        server = RingServer(ring, stuck)
        server.start()
        cl = RingClient(ring, ring.endpoints[0])
        t = threading.Thread(
            target=lambda: cl.submit(b"x", timeout=30), daemon=True
        )
        try:
            t.start()
            time.sleep(0.2)  # the only slot is now leased
            t0 = time.monotonic()
            with pytest.raises(ErrResourceExhausted) as ei:
                cl.submit(b"y", timeout=0.3)
            assert time.monotonic() - t0 < 5
            assert ei.value.status_code == 429
        finally:
            hold.set()
            t.join(timeout=10)
            cl.close()
            server.stop()
            ring.close()

    def test_deadline_leaves_slot_leased_until_ack(self):
        release = threading.Event()

        def slow(frame):
            release.wait(10)
            return b"slow"

        ring = WireRing(1, slots_per_endpoint=1, slot_bytes=4096)
        server = RingServer(ring, slow)
        server.start()
        cl = RingClient(ring, ring.endpoints[0])
        try:
            with pytest.raises(DeadlineExceeded):
                cl.submit(b"x", timeout=0.2)
            # slot still leased: the late response must not collide with
            # a reused slot, so the next submit cannot grab it yet
            with pytest.raises(ErrResourceExhausted):
                cl.submit(b"y", timeout=0.3)
            release.set()  # parent answers; the ack recycles the slot
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                try:
                    payload = cl.submit(b"z", timeout=1.0)
                    break
                except (ErrResourceExhausted, DeadlineExceeded):
                    time.sleep(0.05)
            else:
                pytest.fail("slot never recycled after the late ack")
            assert pickle.loads(payload)[0] == "ok"
        finally:
            release.set()
            cl.close()
            server.stop()
            ring.close()

    def test_ring_backend_merges_remote_stages(self):
        """The worker-side ledger stays conserved across the hop: remote
        stage seconds fold in, the hop residual books to queue."""
        from keto_tpu.telemetry.attribution import (
            TimeLedger,
            reset_current_ledger,
            set_current_ledger,
        )

        def handler(frame):
            from keto_tpu.telemetry.attribution import ledger_mark

            time.sleep(0.02)
            ledger_mark("kernel")
            return wirecodec.encode_check_response(
                np.array([True, False]), "z1"
            )

        ring = WireRing(1, slot_bytes=4096)
        server = RingServer(ring, handler)
        server.start()
        cl = RingClient(ring, ring.endpoints[0])
        try:
            backend = RingBackend(cl)
            req = wirecodec.decode_check_request(
                wirecodec.encode_check_request(
                    np.array([0, 1], dtype=np.int32),
                    np.array([2, 3], dtype=np.int32),
                    lineage="ab" * 8,
                    epoch=4,
                )
            )
            led = TimeLedger()
            token = set_current_ledger(led)
            try:
                allowed = backend.ring_submit(
                    req, req.start, req.target, timeout=10
                )
            finally:
                reset_current_ledger(token)
            assert [bool(v) for v in allowed] == [True, False]
            assert led.stages.get("kernel", 0) >= 0.015
            assert "queue" in led.stages
        finally:
            cl.close()
            server.stop()
            ring.close()


# ---------------------------------------------------------------------------
# ring-mode front: QoS deferred to the parent, no double debit
# ---------------------------------------------------------------------------


class TestRingFront:
    def test_front_defers_qos_to_ring(self):
        """In a wire worker the front must NOT derive/debit ns_counts —
        the parent debits once from the frame's ns column."""
        store = InMemoryTupleStore()
        store.write_relation_tuples(_t("n:o#r@u"))
        mgr = SnapshotManager(store)
        vocab = mgr.snapshot().vocab
        seen = {}

        class FakeRingBackend:
            def ring_submit(self, req, start, target, timeout=None):
                seen["ns"] = req.ns
                return np.array([False] * len(start))

        front = EncodedCheckFront(mgr, FakeRingBackend())
        req = wirecodec.decode_check_request(
            wirecodec.encode_check_request(
                np.array([0], dtype=np.int32),
                np.array([1], dtype=np.int32),
                lineage=vocabsync.lineage_of(vocab),
                epoch=vocabsync.epoch_of(vocab),
                ns=np.array([0], dtype=np.int32),
            )
        )
        got = front.check(req)
        assert list(got) == [False]
        # the ns column crossed the hop intact for the parent's debit
        np.testing.assert_array_equal(seen["ns"], [0])

    def test_parent_front_skips_epoch_gate(self):
        """validate=False (the parent ring consumer): an older-but-same-
        lineage epoch must pass — the worker already gated it."""
        store = InMemoryTupleStore()
        store.write_relation_tuples(_t("n:o#r@u"))
        mgr = SnapshotManager(store)
        vocab = mgr.snapshot().vocab
        lineage = vocabsync.lineage_of(vocab)
        old_epoch = vocabsync.epoch_of(vocab)
        store.write_relation_tuples(_t("n:o2#r@u2"))  # epoch moves on

        class Oracle:
            def check_batch_encoded(
                self, s, t, depths=None, min_version=0, timeout=None,
                ns_counts=None,
            ):
                return np.array([True] * len(s))

        req = wirecodec.decode_check_request(
            wirecodec.encode_check_request(
                np.array([0], dtype=np.int32),
                np.array([1], dtype=np.int32),
                lineage=lineage,
                epoch=old_epoch,
            )
        )
        strict = EncodedCheckFront(mgr, Oracle())
        with pytest.raises(ErrVocabEpochMismatch):
            strict.check(req)
        relaxed = EncodedCheckFront(mgr, Oracle(), validate=False)
        assert list(relaxed.check(req)) == [True]
