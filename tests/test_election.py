"""Lease-based leader election: fencing, clock skew, failed promotions.

The safety property under test is the one the game-day drill relies on:
at most one node passes the write-path fence at any instant, across the
whole double-leader window — the interval where a stale ex-leader still
*believes* it leads (its own clock says the lease is live) while a newer
term already exists on disk. Terms are compared before expiry, so no
clock skew lets a fenced leader write.
"""

import json
import os

import pytest

from keto_tpu.cluster.election import (
    LEASE_FILE,
    ElectionManager,
    LeaseStore,
)
from keto_tpu.faults import FAULTS


class FakeClock:
    def __init__(self, t: float = 1_000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


@pytest.fixture(autouse=True)
def _reset_faults():
    FAULTS.reset()
    yield
    FAULTS.reset()


def manager(store, instance_id, clock, **kw):
    kw.setdefault("lease_ttl_s", 3.0)
    kw.setdefault("heartbeat_interval_s", 0.01)
    return ElectionManager(
        store, instance_id=instance_id, clock=clock, **kw
    )


class TestLeaseStore:
    def test_vacant_acquire_mints_term_one(self, tmp_path):
        clock = FakeClock()
        store = LeaseStore(str(tmp_path), clock=clock)
        lease = store.acquire("a", 3.0, write_url="http://a:1")
        assert lease is not None
        assert lease["term"] == 1
        assert lease["leader_id"] == "a"
        assert store.fence_check("a", 1)
        lineage = store.lineage()
        assert [r["term"] for r in lineage] == [1]
        assert lineage[0]["prev_leader_id"] is None

    def test_live_lease_blocks_other_candidates(self, tmp_path):
        clock = FakeClock()
        store = LeaseStore(str(tmp_path), clock=clock)
        assert store.acquire("a", 3.0) is not None
        assert store.acquire("b", 3.0) is None
        # ...until it expires
        clock.advance(3.5)
        lease = store.acquire("b", 3.0)
        assert lease is not None and lease["term"] == 2

    def test_renew_extends_and_fences(self, tmp_path):
        clock = FakeClock()
        store = LeaseStore(str(tmp_path), clock=clock)
        store.acquire("a", 3.0)
        clock.advance(2.0)
        renewed = store.renew("a", 1, 3.0)
        assert renewed is not None
        assert renewed["expires_at"] == pytest.approx(clock() + 3.0)
        # a newer term on disk fences the old leader's renewal
        clock.advance(3.5)
        store.acquire("b", 3.0)
        assert store.renew("a", 1, 3.0) is None

    def test_release_expires_immediately(self, tmp_path):
        clock = FakeClock()
        store = LeaseStore(str(tmp_path), clock=clock)
        store.acquire("a", 300.0)
        assert store.release("a", 1)
        assert not store.fence_check("a", 1)
        # a successor need not wait out the 300s TTL
        lease = store.acquire("b", 3.0)
        assert lease is not None and lease["term"] == 2
        # releasing with a stale term is a no-op
        assert not store.release("a", 1)

    def test_corrupt_lease_reads_as_vacant(self, tmp_path):
        clock = FakeClock()
        store = LeaseStore(str(tmp_path), clock=clock)
        store.acquire("a", 3.0)
        with open(os.path.join(str(tmp_path), LEASE_FILE), "w") as f:
            f.write("{half a lease")
        assert store.read() is None
        # vacancy only ever delays an election; the next acquire wins —
        # note the lineage keeps its chain even across the corruption
        lease = store.acquire("b", 3.0)
        assert lease is not None and lease["term"] == 1

    def test_lineage_is_strictly_increasing(self, tmp_path):
        clock = FakeClock()
        store = LeaseStore(str(tmp_path), clock=clock)
        for i, who in enumerate(["a", "b", "a", "c"]):
            clock.advance(10.0)
            lease = store.acquire(who, 3.0)
            assert lease is not None and lease["term"] == i + 1
        terms = [r["term"] for r in store.lineage()]
        assert terms == [1, 2, 3, 4]
        chain = [r["prev_term"] for r in store.lineage()]
        assert chain == [0, 1, 2, 3]


class TestClockSkewFencing:
    """Two LeaseStores with different clocks over one directory: the
    double-leader window, driven explicitly."""

    def test_stale_ex_leader_is_fenced_despite_skew(self, tmp_path):
        # A's clock runs 20s behind B's: by A's reckoning its lease is
        # comfortably live for the whole test
        clock_a = FakeClock(1_000.0)
        clock_b = FakeClock(1_020.0)
        store_a = LeaseStore(str(tmp_path), clock=clock_a)
        store_b = LeaseStore(str(tmp_path), clock=clock_b)

        lease = store_a.acquire("a", 10.0)
        assert lease is not None and lease["term"] == 1
        # double-leader window opens: B (whose clock says the lease
        # expired 10s ago) takes over with term 2...
        takeover = store_b.acquire("b", 10.0)
        assert takeover is not None and takeover["term"] == 2
        # ...while A's clock still believes term 1 has ~10s to live.
        # The fence compares terms BEFORE expiry, so A is rejected:
        assert clock_a() < lease["expires_at"]
        assert not store_a.fence_check("a", 1)
        assert store_b.fence_check("b", 2)

    def test_exactly_one_writer_throughout_the_window(self, tmp_path):
        clock_a = FakeClock(1_000.0)
        clock_b = FakeClock(1_020.0)
        store_a = LeaseStore(str(tmp_path), clock=clock_a)
        store_b = LeaseStore(str(tmp_path), clock=clock_b)
        store_a.acquire("a", 10.0)
        # before the takeover: A alone passes the fence
        assert store_a.fence_check("a", 1)
        assert not store_b.fence_check("b", 1)
        store_b.acquire("b", 10.0)
        # after: B alone passes — at no instant did both
        assert not store_a.fence_check("a", 1)
        assert store_b.fence_check("b", 2)

    def test_manager_write_gate_rejects_late_writes(self, tmp_path):
        """The ElectionManager integration of the same property: a
        leader whose lease was taken over answers is_writable()=False
        on the very next mutation, no cached verdicts."""
        clock_a = FakeClock(1_000.0)
        clock_b = FakeClock(1_020.0)
        store_a = LeaseStore(str(tmp_path), clock=clock_a)
        store_b = LeaseStore(str(tmp_path), clock=clock_b)
        em = manager(store_a, "a", clock_a, write_url="http://a:1")
        assert em.ensure_leadership()
        assert em.is_writable()
        store_b.acquire("b", 10.0, write_url="http://b:1")
        # the stale ex-leader's gate slams shut instantly
        assert not em.is_writable()
        # and the rejection carries the new leader's coordinates
        hint = em.leader_hint()
        assert hint == {
            "leader_id": "b",
            "term": 2,
            "read_url": "",
            "write_url": "http://b:1",
        }


class TestElectionManager:
    def test_campaign_wins_vacant_lease_and_promotes(self, tmp_path):
        clock = FakeClock()
        store = LeaseStore(str(tmp_path), clock=clock)
        promoted = []
        em = manager(
            store, "b", clock,
            promote_fn=lambda: promoted.append(True) or {"applied": 0},
        )
        em.run_once()
        assert em.role == "leader"
        assert em.term == 1
        assert promoted == [True]
        assert em.is_writable()
        assert em.leader_hint() is None

    def test_fenced_leader_steps_down_and_retargets(self, tmp_path):
        clock = FakeClock()
        store = LeaseStore(str(tmp_path), clock=clock)
        retargets = []
        em = manager(
            store, "a", clock,
            write_url="http://a:1",
            retarget_fn=retargets.append,
        )
        assert em.ensure_leadership()
        # a rival takes over (e.g. after a lease_stall let the TTL lapse)
        clock.advance(10.0)
        store.acquire("b", 3.0, write_url="http://b:1")
        em.run_once()
        assert em.role == "follower"
        assert em.term == 0
        assert "fenced by b" in em.last_transition["reason"]
        assert [r["write_url"] for r in retargets] == ["http://b:1"]

    def test_failed_promotion_releases_and_reelects(self, tmp_path):
        clock = FakeClock()
        store = LeaseStore(str(tmp_path), clock=clock)
        promoted = []
        em = manager(
            store, "b", clock,
            promote_fn=lambda: promoted.append(True) or {},
        )
        FAULTS.arm("replica.promote_fail")
        em.run_once()
        # the injected promote failure must not wedge the fleet: the
        # lease is released (not left to bake out its TTL)...
        assert em.role == "follower"
        assert "promotion failed" in em.last_transition["reason"]
        assert not store.fence_check("b", 1)
        assert promoted == []
        # ...and the next tick re-elects cleanly with a NEW term
        em.run_once()
        assert em.role == "leader"
        assert em.term == 2
        assert promoted == [True]
        terms = [r["term"] for r in store.lineage()]
        assert terms == [1, 2]

    def test_split_heartbeat_cannot_mint_a_second_term(self, tmp_path):
        clock = FakeClock()
        store = LeaseStore(str(tmp_path), clock=clock)
        assert store.acquire("a", 30.0) is not None
        em = manager(store, "b", clock)
        FAULTS.arm("election.split_heartbeat")
        em.run_once()  # false suspicion -> premature campaign
        # the live lease's flock CAS rejects the early candidacy: no
        # second term, no role change, lineage untouched
        assert em.role == "follower"
        assert [r["term"] for r in store.lineage()] == [1]
        assert em.observed_term == 1
        # with the fault drained, a normal tick just follows the leader
        em.run_once()
        assert em.role == "follower"

    def test_candidacy_rank_orders_by_priority_then_position(self, tmp_path):
        clock = FakeClock()
        store = LeaseStore(str(tmp_path), clock=clock)
        em = manager(store, "b", clock, position_fn=lambda: 50)
        em.observe_peers({
            "members": [
                # the dying leader never counts
                {"instance_id": "L", "role": "leader", "alive": True,
                 "version": 999},
                # better replicated position -> ahead of us
                {"instance_id": "c", "alive": True, "version": 80,
                 "election": {"priority": 0}},
                # dead peers don't rank
                {"instance_id": "d", "alive": False, "version": 500,
                 "election": {"priority": 5}},
                # worse position -> behind us
                {"instance_id": "e", "alive": True, "version": 10,
                 "election": {"priority": 0}},
            ]
        })
        assert em.candidacy_rank() == 1
        # configured priority trumps position
        em.priority = 1
        assert em.candidacy_rank() == 0

    def test_rank_ties_break_on_instance_id(self, tmp_path):
        clock = FakeClock()
        store = LeaseStore(str(tmp_path), clock=clock)
        em = manager(store, "b", clock, position_fn=lambda: 50)
        peers = {
            "members": [
                {"instance_id": "a", "alive": True, "version": 50,
                 "election": {"priority": 0}},
                {"instance_id": "c", "alive": True, "version": 50,
                 "election": {"priority": 0}},
            ]
        }
        em.observe_peers(peers)
        # identical (priority, position): smaller id goes first, so "b"
        # yields to "a" but not to "c" — a total order, no shared slots
        assert em.candidacy_rank() == 1

    def test_clean_stop_releases_for_fast_failover(self, tmp_path):
        clock = FakeClock()
        store = LeaseStore(str(tmp_path), clock=clock)
        em = manager(store, "a", clock)
        assert em.ensure_leadership()
        em.stop(release=True)
        # successor acquires without waiting out the TTL
        lease = store.acquire("b", 3.0)
        assert lease is not None and lease["term"] == 2

    def test_status_surfaces_term_and_lease(self, tmp_path):
        clock = FakeClock()
        store = LeaseStore(str(tmp_path), clock=clock)
        em = manager(store, "a", clock)
        assert em.ensure_leadership()
        doc = em.status()
        assert doc["role"] == "leader"
        assert doc["term"] == 1
        assert doc["observed_term"] == 1
        assert doc["leader_id"] == "a"
        assert doc["lease_expires_in_s"] == pytest.approx(3.0)
        assert doc["transitions"] == 1
        assert doc["last_transition"]["reason"] == "bootstrap"
        assert json.dumps(doc)  # JSON-serializable for /cluster/status
