"""Drives the multi-device sharding tests under an 8-device virtual CPU
mesh. The axon sitecustomize pins the backend at interpreter start, so the
mesh tests need a fresh interpreter with the right env (see conftest note)."""

import os
import subprocess
import sys

import pytest


def _run_on_virtual_mesh(test_file: str) -> None:
    env = dict(os.environ)
    env.update(
        {
            "PALLAS_AXON_POOL_IPS": "",  # skip axon registration
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        }
    )
    proc = subprocess.run(
        [
            sys.executable,
            "-m",
            "pytest",
            os.path.join(os.path.dirname(__file__), test_file),
            "-q",
            "--no-header",
        ],
        env=env,
        capture_output=True,
        text=True,
        timeout=570,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert proc.returncode == 0, (
        f"{test_file} failed\nstdout:\n{proc.stdout}\nstderr:\n{proc.stderr}"
    )
    assert "skipped" not in proc.stdout.lower() or "passed" in proc.stdout


@pytest.mark.timeout(600)
def test_multichip_suite_on_virtual_mesh():
    _run_on_virtual_mesh("test_multichip_sharded.py")


@pytest.mark.timeout(600)
def test_sharded_serving_suite_on_virtual_mesh():
    _run_on_virtual_mesh("test_sharded_serving.py")
