"""Concurrency stress: writers, checkers, expanders, and config reloads
hammering one live registry. The reference runs its suite under `go test
-race` as a separate CI job (reference .circleci/config.yml:57-66); Python
has no race detector, so this is the analog: shake the lock/snapshot/
rebuild machinery under real thread interleavings and assert no exceptions,
no deadlocks, and convergence to the oracle's answers afterward."""

import threading
import time

import pytest

from keto_tpu.driver.factory import new_test_registry
from keto_tpu.engine.check import CheckEngine
from keto_tpu.relationtuple import RelationQuery, RelationTuple, SubjectSet


def t(s: str) -> RelationTuple:
    return RelationTuple.from_string(s)


@pytest.mark.parametrize("freshness", ["strong", "bounded"])
def test_concurrent_writers_and_checkers(freshness):
    reg = new_test_registry(
        namespaces=("videos",),
        values={"engine": {"freshness": freshness, "rebuild_debounce_ms": 0}},
    )
    store = reg.store()
    engine = reg.check_engine()
    for i in range(8):
        store.write_relation_tuples(t(f"videos:g{i}#m@u{i}"))

    stop = threading.Event()
    errors: list[BaseException] = []

    def guard(fn):
        def run():
            try:
                while not stop.is_set():
                    fn()
            except BaseException as e:  # surfaced after join
                errors.append(e)

        return run

    counter = [0]

    def writer():
        i = counter[0] = counter[0] + 1
        store.write_relation_tuples(
            t(f"videos:obj{i % 50}#view@(videos:g{i % 8}#m)")
        )
        if i % 7 == 0:
            store.delete_relation_tuples(
                t(f"videos:obj{i % 50}#view@(videos:g{i % 8}#m)")
            )

    def checker():
        engine.batch_check(
            [t(f"videos:obj{i}#view@u{i % 8}") for i in range(16)]
        )

    def expander():
        reg.expand_engine().build_tree(
            SubjectSet(namespace="videos", object="obj1", relation="view"),
            3,
        )

    threads = [
        threading.Thread(target=guard(fn), daemon=True)
        for fn in (writer, writer, checker, checker, expander)
    ]
    for th in threads:
        th.start()
    time.sleep(3.0)
    stop.set()
    for th in threads:
        th.join(timeout=30)
        assert not th.is_alive(), "stress thread deadlocked"
    assert not errors, errors

    # convergence: once writes quiesce, the engine answers must match the
    # host oracle exactly (bounded freshness catches up)
    oracle = CheckEngine(store, max_depth=5)
    reqs = [
        t(f"videos:obj{i}#view@u{j}") for i in range(20) for j in range(4)
    ]
    expect = [oracle.subject_is_allowed(r) for r in reqs]
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        if engine.batch_check(reqs) == expect:
            break
        time.sleep(0.05)
    assert engine.batch_check(reqs) == expect


def test_batcher_under_concurrent_load():
    reg = new_test_registry(namespaces=("videos",))
    store = reg.store()
    store.write_relation_tuples(t("videos:o#r@alice"))
    checker = reg.checker()  # CheckBatcher over the closure engine
    results: list[bool] = []
    errors: list[BaseException] = []

    def client(i):
        try:
            sub = "alice" if i % 2 == 0 else "bob"
            got = checker.check(t(f"videos:o#r@{sub}"), 0)
            assert got == (i % 2 == 0)
            results.append(got)
        except BaseException as e:
            errors.append(e)

    threads = [
        threading.Thread(target=client, args=(i,), daemon=True)
        for i in range(64)
    ]
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=30)
    assert not errors, errors
    assert len(results) == 64
    reg._batcher.close()


def test_batch_transport_slices_oversized_batches():
    """check_batch must dispatch in max_batch slices — one giant request
    cannot balloon the engine's working set past the cap."""
    reg = new_test_registry(
        namespaces=("videos",), values={"engine": {"max_batch": 8}}
    )
    reg.store().write_relation_tuples(t("videos:o#r@alice"))
    checker = reg.checker()
    reqs = [
        t(f"videos:o#r@{'alice' if i % 3 == 0 else 'bob'}")
        for i in range(50)
    ]
    got = checker.check_batch(reqs)
    assert got == [(i % 3 == 0) for i in range(50)]
    reg._batcher.close()


def test_store_isolation_under_concurrent_tenants():
    """Two registries (tenants) on separate stores: concurrent writes must
    never leak across (the in-process analog of the reference's
    IsolationTest, manager_isolation.go:44-138)."""
    rega = new_test_registry(namespaces=("videos",))
    regb = new_test_registry(namespaces=("videos",))
    errors: list[BaseException] = []

    def load(reg, tag):
        try:
            for i in range(200):
                reg.store().write_relation_tuples(
                    t(f"videos:{tag}{i}#r@u{i}")
                )
        except BaseException as e:
            errors.append(e)

    ta = threading.Thread(target=load, args=(rega, "a"), daemon=True)
    tb = threading.Thread(target=load, args=(regb, "b"), daemon=True)
    ta.start(); tb.start()
    ta.join(timeout=60); tb.join(timeout=60)
    assert not errors, errors
    assert len(rega.store()) == 200 and len(regb.store()) == 200
    a_tuples, _ = rega.store().get_relation_tuples(
        RelationQuery(namespace="videos"), None
    )
    assert all(x.object.startswith("a") for x in a_tuples)
    assert rega.check_engine().subject_is_allowed(t("videos:a1#r@u1"))
    assert not rega.check_engine().subject_is_allowed(t("videos:b1#r@u1"))
