"""Concurrency stress: writers, checkers, expanders, and config reloads
hammering one live registry. The reference runs its suite under `go test
-race` as a separate CI job (reference .circleci/config.yml:57-66); Python
has no race detector, so this is the analog: shake the lock/snapshot/
rebuild machinery under real thread interleavings and assert no exceptions,
no deadlocks, and convergence to the oracle's answers afterward."""

import threading
import time

import pytest

from keto_tpu.driver.factory import new_test_registry
from keto_tpu.engine.check import CheckEngine
from keto_tpu.relationtuple import RelationQuery, RelationTuple, SubjectSet


def t(s: str) -> RelationTuple:
    return RelationTuple.from_string(s)


@pytest.mark.parametrize("freshness", ["strong", "bounded"])
def test_concurrent_writers_and_checkers(freshness):
    reg = new_test_registry(
        namespaces=("videos",),
        values={"engine": {"freshness": freshness, "rebuild_debounce_ms": 0}},
    )
    store = reg.store()
    engine = reg.check_engine()
    for i in range(8):
        store.write_relation_tuples(t(f"videos:g{i}#m@u{i}"))

    stop = threading.Event()
    errors: list[BaseException] = []

    def guard(fn):
        def run():
            try:
                while not stop.is_set():
                    fn()
            except BaseException as e:  # surfaced after join
                errors.append(e)

        return run

    counter = [0]

    def writer():
        i = counter[0] = counter[0] + 1
        store.write_relation_tuples(
            t(f"videos:obj{i % 50}#view@(videos:g{i % 8}#m)")
        )
        if i % 7 == 0:
            store.delete_relation_tuples(
                t(f"videos:obj{i % 50}#view@(videos:g{i % 8}#m)")
            )

    def checker():
        engine.batch_check(
            [t(f"videos:obj{i}#view@u{i % 8}") for i in range(16)]
        )

    def expander():
        reg.expand_engine().build_tree(
            SubjectSet(namespace="videos", object="obj1", relation="view"),
            3,
        )

    threads = [
        threading.Thread(target=guard(fn), daemon=True)
        for fn in (writer, writer, checker, checker, expander)
    ]
    for th in threads:
        th.start()
    time.sleep(3.0)
    stop.set()
    for th in threads:
        th.join(timeout=30)
        assert not th.is_alive(), "stress thread deadlocked"
    assert not errors, errors

    # convergence: once writes quiesce, the engine answers must match the
    # host oracle exactly (bounded freshness catches up)
    oracle = CheckEngine(store, max_depth=5)
    reqs = [
        t(f"videos:obj{i}#view@u{j}") for i in range(20) for j in range(4)
    ]
    expect = [oracle.subject_is_allowed(r) for r in reqs]
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        if engine.batch_check(reqs) == expect:
            break
        time.sleep(0.05)
    assert engine.batch_check(reqs) == expect


def test_batcher_under_concurrent_load():
    reg = new_test_registry(namespaces=("videos",))
    store = reg.store()
    store.write_relation_tuples(t("videos:o#r@alice"))
    checker = reg.checker()  # CheckBatcher over the closure engine
    results: list[bool] = []
    errors: list[BaseException] = []

    def client(i):
        try:
            sub = "alice" if i % 2 == 0 else "bob"
            got = checker.check(t(f"videos:o#r@{sub}"), 0)
            assert got == (i % 2 == 0)
            results.append(got)
        except BaseException as e:
            errors.append(e)

    threads = [
        threading.Thread(target=client, args=(i,), daemon=True)
        for i in range(64)
    ]
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=30)
    assert not errors, errors
    assert len(results) == 64
    reg._batcher.close()


def test_batch_transport_slices_oversized_batches():
    """check_batch must dispatch in max_batch slices — one giant request
    cannot balloon the engine's working set past the cap."""
    reg = new_test_registry(
        namespaces=("videos",), values={"engine": {"max_batch": 8}}
    )
    reg.store().write_relation_tuples(t("videos:o#r@alice"))
    checker = reg.checker()
    reqs = [
        t(f"videos:o#r@{'alice' if i % 3 == 0 else 'bob'}")
        for i in range(50)
    ]
    got = checker.check_batch(reqs)
    assert got == [(i % 3 == 0) for i in range(50)]
    reg._batcher.close()


def test_store_isolation_under_concurrent_tenants():
    """Two registries (tenants) on separate stores: concurrent writes must
    never leak across (the in-process analog of the reference's
    IsolationTest, manager_isolation.go:44-138)."""
    rega = new_test_registry(namespaces=("videos",))
    regb = new_test_registry(namespaces=("videos",))
    errors: list[BaseException] = []

    def load(reg, tag):
        try:
            for i in range(200):
                reg.store().write_relation_tuples(
                    t(f"videos:{tag}{i}#r@u{i}")
                )
        except BaseException as e:
            errors.append(e)

    ta = threading.Thread(target=load, args=(rega, "a"), daemon=True)
    tb = threading.Thread(target=load, args=(regb, "b"), daemon=True)
    ta.start(); tb.start()
    ta.join(timeout=60); tb.join(timeout=60)
    assert not errors, errors
    assert len(rega.store()) == 200 and len(regb.store()) == 200
    a_tuples, _ = rega.store().get_relation_tuples(
        RelationQuery(namespace="videos"), None
    )
    assert all(x.object.startswith("a") for x in a_tuples)
    assert rega.check_engine().subject_is_allowed(t("videos:a1#r@u1"))
    assert not rega.check_engine().subject_is_allowed(t("videos:b1#r@u1"))


def test_interior_churn_under_concurrent_checkers():
    """r5: concurrent interior-edge inserts AND deletes (the overlay's
    re-close path) racing a checker pool — answers must converge to the
    oracle with zero wrong-version crashes and no overlay corruption."""
    import numpy as np

    from keto_tpu.engine.closure import ClosureCheckEngine
    from keto_tpu.graph import SnapshotManager
    from keto_tpu.store import InMemoryTupleStore

    store = InMemoryTupleStore()
    n_groups = 10
    base = []
    for g in range(n_groups):
        base.append(t(f"n:g{g}#m@u{g % 4}"))
        base.append(t(f"n:doc{g % 3}#view@(n:g{g}#m)"))
    for i in range(6):
        base.append(t(f"n:g{i}#m@(n:g{i + 2}#m)"))
    store.write_relation_tuples(*base)
    engine = ClosureCheckEngine(
        SnapshotManager(store), max_depth=5, rebuild_debounce_s=0.0
    )
    reqs = [t(f"n:doc{d}#view@u{u}") for d in range(3) for u in range(4)]
    engine.batch_check(reqs)

    stop = threading.Event()
    errors: list = []

    def writer(seed):
        rng = np.random.default_rng(seed)
        try:
            while not stop.is_set():
                a, b = (int(x) for x in rng.integers(n_groups, size=2))
                edge = t(f"n:g{a}#m@(n:g{b}#m)")
                if rng.random() < 0.5:
                    store.write_relation_tuples(edge)
                else:
                    store.delete_relation_tuples(edge)
        except Exception as e:  # pragma: no cover - the assertion target
            errors.append(e)

    def checker():
        try:
            while not stop.is_set():
                engine.batch_check(reqs)
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [
        threading.Thread(target=writer, args=(s,), daemon=True)
        for s in range(3)
    ] + [threading.Thread(target=checker, daemon=True) for _ in range(2)]
    for th in threads:
        th.start()
    time.sleep(2.5)
    stop.set()
    for th in threads:
        th.join(timeout=30)
        assert not th.is_alive(), "stress thread wedged"
    assert not errors, errors

    # convergence: quiesced answers equal the oracle at the live version
    engine.wait_for_version(store.version, timeout_s=60)
    oracle = CheckEngine(store, max_depth=5)
    assert engine.batch_check(reqs) == oracle.batch_check(reqs)
