"""Overload-control plane (engine/overload.py) + the client-side retry
discipline (client/retry.py, client/hedge.py) and the wire plumbing.

Deterministic drills, fake clocks throughout: the AIMD limiter converges
up under healthy latency and backs off multiplicatively under inflation,
the CoDel detector flips FIFO->adaptive-LIFO and culls aged entries (but
never critical ones), the brownout ladder escalates one observable rung
at a time and cannot flap thanks to hysteresis, `critical` is never shed
by the ladder, the SRE accepts/requests throttle math is exact, retry
budgets cap client amplification, Retry-After hints floor the backoff
and survive the REST ceil fix, hedges are suppressed when the primary
was shed, and criticality round-trips through the REST header / gRPC
metadata into the batcher. Plus the serving surfaces: /debug/overload,
the hedge_suppressed flag on /debug/autotune, keto_overload_* metric
families, and the config schema keys.
"""

import threading
import time

import httpx
import pytest

from keto_tpu.client.hedge import HedgePolicy, Hedger, is_overload_error
from keto_tpu.client.retry import (
    RetryBudget,
    RetryPolicy,
    retry_after_hint_s,
    run_with_retry,
)
from keto_tpu.driver.config import CONFIG_SCHEMA, Config, DEFAULTS
from keto_tpu.engine.overload import (
    CRITICAL,
    DEFAULT,
    SHEDDABLE,
    STATE_BOUNDED_STALE,
    STATE_HEDGE_SUPPRESS,
    STATE_NORMAL,
    STATE_SHED_DEFAULT,
    STATE_SHED_SHEDDABLE,
    AdaptiveLimiter,
    AdaptiveThrottle,
    BrownoutController,
    OverloadController,
    parse_criticality,
)
from keto_tpu.relationtuple import RelationTuple, SubjectID
from keto_tpu.telemetry import MetricsRegistry
from keto_tpu.telemetry.flight import FlightRecorder
from keto_tpu.utils.errors import ErrResourceExhausted


class _Clock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt
        return self.t


def _tup(i=0):
    return RelationTuple("n", f"o{i}", "view", SubjectID("u"))


# -- criticality parsing ------------------------------------------------------


class TestParseCriticality:
    def test_known_classes_normalized(self):
        assert parse_criticality("critical") == CRITICAL
        assert parse_criticality(" Sheddable ") == SHEDDABLE
        assert parse_criticality("DEFAULT") == DEFAULT

    def test_unknown_and_empty_fall_back_to_default(self):
        # a typo'd header must not change the answer, only shed priority
        assert parse_criticality("importantest") == DEFAULT
        assert parse_criticality("") == DEFAULT
        assert parse_criticality(None) == DEFAULT

    def test_configured_default_class(self):
        assert parse_criticality(None, default=SHEDDABLE) == SHEDDABLE
        assert parse_criticality("nope", default=SHEDDABLE) == SHEDDABLE
        # an explicit wire value still wins over the configured default
        assert parse_criticality("critical", default=SHEDDABLE) == CRITICAL


# -- AIMD limiter + CoDel -----------------------------------------------------


class TestAdaptiveLimiter:
    def test_additive_increase_under_healthy_latency(self):
        clk = _Clock()
        lim = AdaptiveLimiter(
            initial=100, target_delay_s=0.1, interval_s=0.1, clock=clk
        )
        for _ in range(10):
            clk.advance(0.2)
            lim.observe(0.005, 0.005)
        assert lim.limit == pytest.approx(100 + 10 * lim.additive)
        assert lim.decreases == 0 and not lim.overloaded

    def test_multiplicative_decrease_on_inflation(self):
        clk = _Clock()
        lim = AdaptiveLimiter(
            initial=100, target_delay_s=0.1, interval_s=0.1,
            tolerance=2.0, clock=clk,
        )
        for _ in range(5):  # learn a ~5ms baseline
            clk.advance(0.2)
            lim.observe(0.005)
        base_limit = lim.limit
        for _ in range(5):  # 50ms >> 2x baseline, still under CoDel target
            clk.advance(0.2)
            lim.observe(0.05)
        assert lim.limit < base_limit
        assert lim.decreases >= 1

    def test_convergence_floor_is_min_limit(self):
        clk = _Clock()
        lim = AdaptiveLimiter(
            initial=64, min_limit=8, target_delay_s=0.01,
            interval_s=0.1, clock=clk,
        )
        for _ in range(200):
            clk.advance(0.2)
            lim.observe(1.0)  # hopeless overload
        assert lim.limit == 8.0

    def test_codel_sustain_flips_lifo_and_cull(self):
        clk = _Clock()
        lim = AdaptiveLimiter(
            initial=100, target_delay_s=0.1, interval_s=0.1, clock=clk
        )
        # one above-target sample is a tolerated burst, not overload
        lim.observe(0.2)
        assert not lim.overloaded and lim.cull_age_s() is None
        clk.advance(0.15)  # past interval_s with delay still above target
        lim.observe(0.2)
        assert lim.overloaded and lim.lifo()
        assert lim.cull_age_s() == pytest.approx(0.1)
        # a below-target sample ends the episode immediately
        lim.observe(0.01)
        assert not lim.overloaded and lim.cull_age_s() is None

    def test_baseline_frozen_while_overloaded(self):
        clk = _Clock()
        lim = AdaptiveLimiter(
            initial=100, target_delay_s=0.05, interval_s=0.1, clock=clk
        )
        lim.observe(0.005)
        clk.advance(0.2)
        lim.observe(0.2)
        clk.advance(0.2)
        lim.observe(0.2)  # sustained: overloaded
        assert lim.overloaded
        frozen = lim._baseline
        clk.advance(0.2)
        lim.observe(5.0)
        # the storm must not teach the baseline what "good" looks like
        assert lim._baseline == pytest.approx(frozen)


# -- brownout ladder ----------------------------------------------------------


class TestBrownoutLadder:
    def _ladder(self, clk, **kw):
        kw.setdefault("up_thresholds", (1.0, 1.5, 2.0, 3.0))
        kw.setdefault("hysteresis_s", 1.0)
        kw.setdefault("min_dwell_s", 0.05)
        return BrownoutController(clock=clk, **kw)

    def test_escalates_one_rung_per_dwell_never_skipping(self):
        clk = _Clock()
        b = self._ladder(clk)
        seen = [b.update(99.0, clk.t)]  # pressure far past every rung
        for _ in range(6):
            clk.advance(0.06)
            seen.append(b.update(99.0, clk.t))
        # every rung visited in order: 1, 2, 3, 4, then pinned at 4
        assert seen[:5] == [1, 2, 3, 4, 4]
        assert b.transitions_up == 4

    def test_shed_order_and_critical_exemption(self):
        clk = _Clock()
        b = self._ladder(clk)
        b.state = STATE_SHED_SHEDDABLE
        assert b.should_shed(SHEDDABLE)
        assert not b.should_shed(DEFAULT)
        assert not b.should_shed(CRITICAL)
        b.state = STATE_SHED_DEFAULT
        assert b.should_shed(SHEDDABLE) and b.should_shed(DEFAULT)
        # the ladder's contract: critical is NEVER shed here, only by
        # the max_queue hard backstop
        assert not b.should_shed(CRITICAL)

    def test_degradations_by_rung(self):
        clk = _Clock()
        b = self._ladder(clk)
        assert not b.hedge_suppressed() and not b.stale_ok()
        b.state = STATE_HEDGE_SUPPRESS
        assert b.hedge_suppressed() and not b.stale_ok()
        b.state = STATE_BOUNDED_STALE
        assert b.hedge_suppressed() and b.stale_ok()

    def test_hysteresis_prevents_flapping(self):
        clk = _Clock()
        b = self._ladder(clk)
        b.update(1.2, clk.t)
        assert b.state == 1
        # pressure drops below down_ratio * threshold, but bounces back
        # above it before the hysteresis window elapses: no step-down
        for _ in range(20):
            clk.advance(0.4)
            b.update(0.1, clk.t)
            clk.advance(0.4)
            b.update(0.9, clk.t)
        assert b.state == 1 and b.transitions_down == 0
        # held quiet for the full window: exactly one step down
        clk.advance(0.4)
        b.update(0.1, clk.t)
        clk.advance(1.1)
        b.update(0.1, clk.t)
        assert b.state == 0 and b.transitions_down == 1

    def test_step_down_one_rung_per_quiet_window(self):
        clk = _Clock()
        b = self._ladder(clk, min_dwell_s=0.0)
        for _ in range(4):
            clk.advance(0.01)
            b.update(99.0, clk.t)
        assert b.state == 4
        # a long quiet stretch steps down one rung per hysteresis window,
        # not straight to zero (the first quiet sample only STARTS the
        # below-threshold window)
        states = []
        for _ in range(6):
            clk.advance(1.05)
            states.append(b.update(0.0, clk.t))
        assert states == [4, 3, 2, 1, 0, 0]

    def test_idle_decay_via_current(self):
        clk = _Clock()
        b = self._ladder(clk)
        b.update(1.2, clk.t)
        assert b.state == 1
        # zero traffic, zero updates: current() applies idle decay
        clk.advance(5.0)
        assert b.current(clk.t) == 0

    def test_transitions_recorded_in_flight_and_history(self):
        clk = _Clock()
        flight = FlightRecorder(capacity=64, clock=clk)
        b = self._ladder(clk, flight=flight)
        b.update(1.2, clk.t)
        clk.advance(2.0)
        b.update(0.0, clk.t)  # starts the quiet window
        clk.advance(1.1)
        b.update(0.0, clk.t)  # held for a full window: steps down
        hist = b.history()
        assert [h["direction"] for h in hist] == ["down", "up"]
        assert hist[1]["from"] == "normal" and hist[1]["to"] == "hedge_suppress"
        kinds = [r.get("kind") for r in flight.records()]
        assert kinds.count("overload") == 2

    def test_threshold_validation(self):
        with pytest.raises(ValueError):
            BrownoutController(up_thresholds=(1.0, 1.5))
        with pytest.raises(ValueError):
            BrownoutController(up_thresholds=(1.0, 1.5, 1.5, 3.0))


# -- SRE adaptive throttle ----------------------------------------------------


class TestAdaptiveThrottle:
    def test_zero_rejects_while_accepts_keep_up(self):
        clk = _Clock()
        th = AdaptiveThrottle(window_s=10.0, k=2.0, clock=clk)
        for _ in range(100):
            th.on_request()
            th.on_accept()
        assert th.reject_probability() == 0.0

    def test_formula_exact(self):
        clk = _Clock()
        th = AdaptiveThrottle(window_s=10.0, k=2.0, clock=clk)
        for _ in range(100):
            th.on_request()
        for _ in range(10):
            th.on_accept()
        # max(0, (reqs - K*accs) / (reqs + 1)) = (100 - 20) / 101
        assert th.reject_probability() == pytest.approx(80 / 101)

    def test_window_slides_old_buckets_out(self):
        clk = _Clock()
        th = AdaptiveThrottle(window_s=5.0, bucket_s=1.0, clock=clk)
        for _ in range(50):
            th.on_request()
        assert th.reject_probability() > 0.9
        clk.advance(10.0)  # everything aged out of the window
        assert th.totals() == (0, 0)
        assert th.reject_probability() == 0.0


# -- client retry discipline --------------------------------------------------


class TestRetryBudget:
    def test_burst_then_exhaustion(self):
        budget = RetryBudget(ratio=0.1, burst=5.0)
        spent = sum(1 for _ in range(20) if budget.spend())
        assert spent == 5  # the cold-start burst, then dry
        assert budget.exhausted == 15

    def test_deposits_cap_amplification_at_ratio(self):
        budget = RetryBudget(ratio=0.1, burst=1.0)
        retries = 0
        for _ in range(1000):
            budget.on_request()
            if budget.spend():
                retries += 1
        # steady state: ~1 retry per 10 requests (plus the 1-token burst)
        assert retries <= 1000 * 0.1 + 1

    def test_tokens_clamped_to_burst(self):
        budget = RetryBudget(ratio=0.5, burst=2.0)
        for _ in range(100):
            budget.on_request()
        assert budget.tokens() == 2.0


class _Shed(ErrResourceExhausted):
    pass


class TestRunWithRetry:
    def test_retry_after_hint_floors_backoff(self):
        sleeps = []
        policy = RetryPolicy(
            max_attempts=3, base_delay_s=0.001, jitter=0.0,
            sleep=sleeps.append,
        )
        err = _Shed("shed")
        err.retry_after_s = 0.5
        calls = []

        def attempt(_remaining):
            calls.append(1)
            if len(calls) < 3:
                raise err
            return "ok"

        assert retry_after_hint_s(err) == 0.5
        out = run_with_retry(attempt, policy, lambda e: True)
        assert out == "ok"
        # the server asked for >= 0.5s of quiet; nominal backoff was ~1ms
        assert len(sleeps) == 2 and all(s >= 0.5 for s in sleeps)

    def test_budget_exhaustion_stops_retrying(self):
        policy = RetryPolicy(
            max_attempts=10, base_delay_s=0.0, jitter=0.0, sleep=lambda s: None
        )
        budget = RetryBudget(ratio=0.0, burst=1.0)  # exactly one retry
        calls = []

        def attempt(_remaining):
            calls.append(1)
            raise _Shed("still overloaded")

        with pytest.raises(_Shed):
            run_with_retry(attempt, policy, lambda e: True, budget=budget)
        # first attempt + the single budgeted retry; 8 permitted attempts
        # were NOT taken — the budget refused to amplify the overload
        assert len(calls) == 2


class TestHedgeSuppression:
    def test_is_overload_error_shapes(self):
        http = _Shed("x")  # KetoError: carries status_code=429
        assert http.status_code == 429
        assert is_overload_error(http)

        class _Typed(Exception):
            grpc_code = "RESOURCE_EXHAUSTED"

        assert is_overload_error(_Typed())

        class _Code:
            name = "RESOURCE_EXHAUSTED"

        class _Rpc(Exception):
            def code(self):
                return _Code()

        assert is_overload_error(_Rpc())
        assert not is_overload_error(None)
        assert not is_overload_error(ValueError("boom"))

    def _counters(self):
        class _C:
            def __init__(self):
                self.n = 0

            def inc(self, v=1):
                self.n += v

        return tuple(_C() for _ in range(4))

    def test_shed_primary_suppresses_hedge(self):
        fired, won, wasted, suppressed = c = self._counters()
        hedge_ran = threading.Event()
        with Hedger(HedgePolicy(delay_s=0.01), counters=c) as h:
            with pytest.raises(_Shed):
                h.call(
                    lambda: (_ for _ in ()).throw(_Shed("shed")),
                    hedge=lambda: hedge_ran.set() or True,
                )
        assert suppressed.n == 1 and fired.n == 0
        assert not hedge_ran.wait(0.05)  # the duplicate never launched

    def test_slow_primary_still_hedges(self):
        fired, won, wasted, suppressed = c = self._counters()
        release = threading.Event()
        with Hedger(HedgePolicy(delay_s=0.01), counters=c) as h:
            out = h.call(lambda: release.wait(5) and "slow", hedge=lambda: "fast")
            release.set()
        assert out.result == "fast" and out.hedged
        assert fired.n == 1 and suppressed.n == 0

    def test_suppressed_counter_registered(self):
        from keto_tpu.telemetry.metrics import hedge_counters

        reg = MetricsRegistry()
        counters = hedge_counters(reg)
        assert len(counters) == 4
        counters[3].inc()
        assert "keto_hedge_suppressed_overload_total 1" in reg.expose()


# -- the controller facade ----------------------------------------------------


def _controller(clk, metrics=None, flight=None, enabled_fn=None):
    return OverloadController(
        max_queue=1_000_000,  # backstop out of reach: ladder only
        limiter=AdaptiveLimiter(
            initial=100, target_delay_s=0.05, interval_s=0.05, clock=clk
        ),
        brownout=BrownoutController(
            hysteresis_s=0.5, min_dwell_s=0.02, flight=flight, clock=clk
        ),
        throttle=AdaptiveThrottle(window_s=5.0, clock=clk),
        metrics=metrics,
        flight=flight,
        enabled_fn=enabled_fn,
        clock=clk,
        rand=lambda: 0.5,
    )


def _storm(ctl, clk, ticks=60, delay=1.0):
    """Drive sustained over-target latency + admissions at every class."""
    shed = {CRITICAL: 0, DEFAULT: 0, SHEDDABLE: 0}
    for _ in range(ticks):
        clk.advance(0.03)
        ctl.observe(delay)
        for crit in (CRITICAL, DEFAULT, SHEDDABLE):
            if ctl.admit(5000, crit) is not None:
                shed[crit] += 1
    return shed


class TestOverloadController:
    def test_storm_sheds_ordered_never_critical(self):
        clk = _Clock()
        flight = FlightRecorder(capacity=256, clock=clk)
        ctl = _controller(clk, flight=flight)
        shed = _storm(ctl, clk)
        assert ctl.state() == STATE_SHED_DEFAULT
        assert shed[CRITICAL] == 0
        assert shed[SHEDDABLE] > shed[DEFAULT] > 0
        snap = ctl.snapshot()
        assert snap["sheds_by_class"][CRITICAL] == 0
        assert snap["state_name"] == "shed_default"

    def test_recovery_steps_down_within_hysteresis_windows(self):
        clk = _Clock()
        ctl = _controller(clk)
        _storm(ctl, clk)
        assert ctl.state() >= STATE_SHED_SHEDDABLE
        # healthy traffic: one rung down per 0.5s hysteresis window
        for _ in range(200):
            clk.advance(0.03)
            ctl.observe(0.001)
            ctl.admit(0, DEFAULT)
        assert ctl.state() == STATE_NORMAL
        # and everything is admitted again
        assert ctl.admit(0, SHEDDABLE) is None

    def test_disabled_means_admit_everything(self):
        clk = _Clock()
        enabled = [False]
        ctl = _controller(clk, enabled_fn=lambda: enabled[0])
        shed = _storm(ctl, clk)
        assert shed == {CRITICAL: 0, DEFAULT: 0, SHEDDABLE: 0}
        assert ctl.state() == STATE_NORMAL
        assert ctl.snapshot()["enabled"] is False
        # the kill switch is live: flipping it on engages the plane
        enabled[0] = True
        shed = _storm(ctl, clk)
        assert shed[SHEDDABLE] > 0

    def test_metrics_families_registered_and_counting(self):
        clk = _Clock()
        reg = MetricsRegistry()
        ctl = _controller(clk, metrics=reg)
        _storm(ctl, clk)
        text = reg.expose()
        for fam in (
            "keto_overload_state",
            "keto_overload_limit",
            "keto_overload_sheds_total",
            "keto_overload_transitions_total",
        ):
            assert fam in text, fam
        assert 'keto_overload_sheds_total{criticality="sheddable"}' in text
        assert 'keto_overload_transitions_total{direction="up"}' in text

    def test_flight_records_every_transition(self):
        clk = _Clock()
        flight = FlightRecorder(capacity=256, clock=clk)
        ctl = _controller(clk, flight=flight)
        _storm(ctl, clk)
        for _ in range(200):
            clk.advance(0.03)
            ctl.observe(0.001)
            ctl.admit(0, DEFAULT)
        evs = [r for r in flight.records() if r.get("kind") == "overload"]
        dirs = {e["direction"] for e in evs}
        assert dirs == {"up", "down"}
        assert len(evs) == len(ctl.history())


# -- batcher integration ------------------------------------------------------


class _GateEngine:
    """batch_check blocks until released; records dispatch order."""

    def __init__(self):
        self.release = threading.Event()
        self.batches: list = []

    def batch_check(self, requests, depths=None):
        self.release.wait(10)
        self.batches.append([r.object for r in requests])
        return [True] * len(requests)


class _StubOverload:
    """Degradation-query stub: admits everything, culls/LIFO on demand."""

    def __init__(self, cull=None, use_lifo=False):
        self.cull = cull
        self.use_lifo = use_lifo
        self.culled = 0

    def admit(self, queue_len, criticality=DEFAULT):
        return None

    def observe(self, queue_delay_s, service_s=0.0):
        pass

    def lifo(self):
        return self.use_lifo

    def cull_age_s(self):
        return self.cull

    def note_culled(self, n):
        self.culled += n

    def stale_ok(self):
        return False

    def snapshot(self):
        return {}


class TestBatcherIntegration:
    def _spin(self, batcher, i, crit, results):
        def run():
            try:
                results[i] = batcher.check(
                    _tup(i), timeout=10, criticality=crit
                )
            except BaseException as e:
                results[i] = e

        t = threading.Thread(target=run, daemon=True)
        t.start()
        return t

    def test_codel_cull_exempts_critical(self):
        from keto_tpu.engine.batcher import CheckBatcher

        ov = _StubOverload(cull=0.01)
        eng = _GateEngine()
        b = CheckBatcher(eng, max_batch=8, window_s=0.0, overload=ov)
        results: dict = {}
        try:
            # occupy the dispatcher inside the (blocked) engine call
            warm = self._spin(b, 0, DEFAULT, results)
            time.sleep(0.05)
            t1 = self._spin(b, 1, CRITICAL, results)
            t2 = self._spin(b, 2, SHEDDABLE, results)
            time.sleep(0.1)  # both queued well past the 10ms cull age
            eng.release.set()
            for t in (warm, t1, t2):
                t.join(timeout=10)
            # the sheddable entry was culled with the typed 429 ...
            assert isinstance(results[2], ErrResourceExhausted)
            assert "culled" in str(results[2])
            # ... while the critical one, just as aged, was served: only
            # the max_queue backstop may ever fail critical work
            assert results[1] is True
            assert ov.culled == 1
        finally:
            eng.release.set()
            b.close()

    def test_adaptive_lifo_serves_newest_first(self):
        from keto_tpu.engine.batcher import CheckBatcher

        ov = _StubOverload(use_lifo=True)
        eng = _GateEngine()
        b = CheckBatcher(eng, max_batch=1, window_s=0.0, overload=ov)
        results: dict = {}
        try:
            warm = self._spin(b, 0, DEFAULT, results)
            time.sleep(0.05)
            threads = []
            for i in (1, 2, 3):
                threads.append(self._spin(b, i, DEFAULT, results))
                time.sleep(0.02)  # strictly ordered enqueue times
            eng.release.set()
            for t in [warm] + threads:
                t.join(timeout=10)
            # max_batch=1: after the warm batch, dispatch order is the
            # REVERSE of arrival — newest entries still meet deadlines
            assert eng.batches[1:] == [["o3"], ["o2"], ["o1"]]
        finally:
            eng.release.set()
            b.close()

    def test_criticality_threaded_into_admission(self):
        from keto_tpu.engine.batcher import CheckBatcher

        seen = []

        class _Recorder(_StubOverload):
            def admit(self, queue_len, criticality=DEFAULT):
                seen.append(criticality)
                return None

        eng = _GateEngine()
        eng.release.set()
        b = CheckBatcher(eng, max_batch=8, window_s=0.0, overload=_Recorder())
        try:
            b.check(_tup(), timeout=10, criticality=SHEDDABLE)
            b.check_batch([_tup()], timeout=10, criticality=CRITICAL)
        finally:
            b.close()
        assert seen == [SHEDDABLE, CRITICAL]

    def test_shed_raises_typed_429_with_reason(self):
        from keto_tpu.engine.batcher import CheckBatcher

        class _Shedder(_StubOverload):
            def admit(self, queue_len, criticality=DEFAULT):
                return "brownout"

        eng = _GateEngine()
        eng.release.set()
        b = CheckBatcher(eng, max_batch=8, window_s=0.0, overload=_Shedder())
        try:
            with pytest.raises(ErrResourceExhausted) as ei:
                b.check(_tup(), timeout=10, criticality=SHEDDABLE)
            assert "brownout" in str(ei.value)
            assert ei.value.status_code == 429
        finally:
            b.close()


# -- wire plumbing ------------------------------------------------------------


class TestWirePlumbing:
    def test_rest_retry_after_rounds_up_never_zero(self):
        from keto_tpu.api.rest import _json_error

        err = ErrResourceExhausted("overloaded")
        err.retry_after_s = 0.2
        # sub-second hints round UP: "Retry-After: 0" invites the
        # immediate re-arrival the header exists to prevent
        assert _json_error(err).headers["Retry-After"] == "1"
        err.retry_after_s = 1.5
        assert _json_error(err).headers["Retry-After"] == "2"
        err.retry_after_s = None
        assert _json_error(err).headers["Retry-After"] == "1"

    def test_grpc_metadata_criticality(self):
        from keto_tpu.api.services import (
            CRITICALITY_METADATA_KEY,
            _criticality_from_metadata,
        )

        class _Ctx:
            def __init__(self, md):
                self._md = md

            def invocation_metadata(self):
                return self._md

        assert (
            _criticality_from_metadata(
                _Ctx(((CRITICALITY_METADATA_KEY, "sheddable"),))
            )
            == SHEDDABLE
        )
        assert _criticality_from_metadata(_Ctx(())) == DEFAULT
        assert (
            _criticality_from_metadata(_Ctx(()), default=SHEDDABLE)
            == SHEDDABLE
        )
        assert (
            _criticality_from_metadata(
                _Ctx(((CRITICALITY_METADATA_KEY, "bogus"),))
            )
            == DEFAULT
        )

    def test_registry_default_criticality_from_config(self):
        from keto_tpu.driver.registry import Registry

        reg = Registry(
            Config(
                values={
                    "namespaces": [{"id": 1, "name": "n"}],
                    "overload": {"default_criticality": "sheddable"},
                },
                env={},
            )
        )
        assert reg.default_criticality() == SHEDDABLE


# -- config surface -----------------------------------------------------------


class TestConfigSurface:
    def test_defaults_present_and_off_by_default(self):
        assert DEFAULTS["overload.enabled"] is False
        for key in (
            "overload.target_delay_ms",
            "overload.interval_ms",
            "overload.min_limit",
            "overload.hysteresis_ms",
            "overload.dwell_ms",
            "overload.throttle_window_s",
            "overload.throttle_k",
            "overload.default_criticality",
        ):
            assert key in DEFAULTS, key

    def test_schema_gates_default_criticality(self):
        props = CONFIG_SCHEMA["properties"]["overload"]["properties"]
        # a blanket "critical" default would defeat the ladder entirely
        assert props["default_criticality"]["enum"] == [
            "default",
            "sheddable",
        ]
        assert props["enabled"]["type"] == "boolean"

    def test_config_reads_overload_keys(self):
        cfg = Config(values={}, env={})
        assert cfg.get("overload.enabled", default=False) is False
        cfg2 = Config(values={"overload": {"enabled": True}}, env={})
        assert cfg2.get("overload.enabled", default=False) is True


# -- serving surfaces (live server) -------------------------------------------


@pytest.fixture(scope="module")
def overload_server():
    from tests.test_api_server import ServerFixture

    cfg = Config(
        values={
            "namespaces": [{"id": 1, "name": "n"}],
            "log": {"level": "error"},
            "serve": {
                "read": {"port": 0, "host": "127.0.0.1"},
                "write": {"port": 0, "host": "127.0.0.1"},
            },
            # generous targets: the plane is ON but must stay at state 0
            # under this test's trickle of traffic
            "overload": {"enabled": True, "target_delay_ms": 5000.0},
        },
        env={},
    )
    s = ServerFixture(cfg)
    yield s
    s.stop()


class TestServingSurfaces:
    def test_debug_overload_snapshot(self, overload_server):
        base = f"http://127.0.0.1:{overload_server.read_port}"
        with httpx.Client(base_url=base, timeout=60) as c:
            # 403 = answered "not allowed" (no tuples written) — the
            # check went through the full admission path either way
            assert c.get("/check", params={
                "namespace": "n", "object": "o", "relation": "view",
                "subject_id": "u",
            }).status_code in (200, 403)
            doc = c.get("/debug/overload").json()
            assert doc["enabled"] is True
            assert doc["state"] == 0 and doc["state_name"] == "normal"
            assert doc["limiter"]["limit"] > 0
            assert doc["brownout"]["ladder"][3] == "shed_sheddable"
            assert doc["sheds_by_class"][CRITICAL] == 0
            assert isinstance(doc["history"], list)
            # the overload families are live on /metrics
            text = c.get("/metrics").text
            assert "keto_overload_state 0" in text
            assert "keto_overload_limit" in text

    def test_rest_criticality_header_round_trip(self, overload_server):
        checker = overload_server.registry.checker()
        seen = []
        orig = checker.check

        def spy(request, *a, **kw):
            seen.append(kw.get("criticality"))
            return orig(request, *a, **kw)

        checker.check = spy
        base = f"http://127.0.0.1:{overload_server.read_port}"
        try:
            with httpx.Client(base_url=base, timeout=60) as c:
                params = {
                    "namespace": "n", "object": "o", "relation": "view",
                    "subject_id": "u",
                }
                c.get("/check", params=params,
                      headers={"X-Request-Criticality": "sheddable"})
                c.get("/check", params=params,
                      headers={"X-Request-Criticality": "CRITICAL"})
                c.get("/check", params=params,
                      headers={"X-Request-Criticality": "bogus"})
                c.get("/check", params=params)
        finally:
            checker.check = orig
        assert seen == [SHEDDABLE, CRITICAL, DEFAULT, DEFAULT]

    def test_debug_autotune_reports_hedge_suppression(self, overload_server):
        base = f"http://127.0.0.1:{overload_server.read_port}"
        with httpx.Client(base_url=base, timeout=60) as c:
            doc = c.get("/debug/autotune").json()
            # state 0: hedges advertised as usual
            assert doc["hedge_suppressed"] is False
        # force the ladder onto rung 1+: the advertisement must vanish
        ctl = overload_server.registry._overload
        assert ctl is not None
        ctl.brownout.state = STATE_HEDGE_SUPPRESS
        ctl.brownout._last_update = time.monotonic() + 3600  # pin: no decay
        try:
            with httpx.Client(base_url=base, timeout=60) as c:
                doc = c.get("/debug/autotune").json()
                assert doc["hedge_suppressed"] is True
                knobs = doc.get("knobs") or {}
                if "hedge_delay_ms" in knobs:
                    assert knobs["hedge_delay_ms"]["value"] is None
        finally:
            ctl.brownout.state = STATE_NORMAL
            ctl.brownout._last_update = None
