"""Columnar check path parity suite (PR 3): the zero-copy wire->vocab->
kernel batch path must be answer-identical to the per-tuple convert path
and to the host oracle — through every layer it touches.

- CheckColumns decode/validate semantics (malformed rows must 400, not
  crash or mis-answer; unicode namespaces round-trip)
- fuzzed parity: random graphs + random batches through batch_check
  (per-tuple), batch_check_columns, CheckBatcher.check_batch_columnar,
  and CheckBatcher.check_batch_encoded, all against CheckEngine (oracle)
- breaker-open fallback: an encoded/columnar batch re-answered by the
  host oracle from lazily materialized tuples, answers unchanged
- encoded-cache correctness across writes (snapshot-version stamps)
- live-server REST + gRPC columnar transports: parity with the per-tuple
  transport, malformed bodies rejected with 400/INVALID_ARGUMENT
"""

import asyncio
import json
import threading

import grpc
import httpx
import numpy as np
import pytest

from keto_tpu.api import acl_pb2, check_service_pb2
from keto_tpu.api.services import CheckServiceStub
from keto_tpu.driver import Config, Registry
from keto_tpu.engine.batcher import CheckBatcher
from keto_tpu.engine.check import CheckEngine
from keto_tpu.engine.closure import ClosureCheckEngine
from keto_tpu.engine.device import DeviceCheckEngine
from keto_tpu.engine.fallback import DeviceFallbackEngine
from keto_tpu.faults import FAULTS
from keto_tpu.graph import SnapshotManager
from keto_tpu.relationtuple import (
    CheckColumns,
    RelationTuple,
    SubjectID,
    SubjectSet,
)
from keto_tpu.store import InMemoryTupleStore
from keto_tpu.utils.errors import ErrMalformedInput

# unicode namespaces ride every fuzz round: the columnar path must carry
# them byte-identically through proto/json/vocab
_NAMESPACES = ("n", "ns-日本語", "grüße")
_RELATIONS = ("view", "edit", "member")


def _t(s: str) -> RelationTuple:
    return RelationTuple.from_string(s)


def _random_store(rng, n_tuples=150):
    store = InMemoryTupleStore()
    tuples = []
    seen = set()
    while len(tuples) < n_tuples:
        ns = _NAMESPACES[rng.integers(len(_NAMESPACES))]
        obj = f"o{rng.integers(12)}"
        rel = _RELATIONS[rng.integers(len(_RELATIONS))]
        if rng.random() < 0.35:
            subject = SubjectSet(
                namespace=_NAMESPACES[rng.integers(len(_NAMESPACES))],
                object=f"o{rng.integers(12)}",
                relation=_RELATIONS[rng.integers(len(_RELATIONS))],
            )
        else:
            subject = SubjectID(id=f"u{rng.integers(20)}")
        tup = RelationTuple(
            namespace=ns, object=obj, relation=rel, subject=subject
        )
        if str(tup) not in seen:
            seen.add(str(tup))
            tuples.append(tup)
    store.write_relation_tuples(*tuples)
    return store


def _random_requests(rng, k):
    """Random check batch: existing-ish keys plus guaranteed vocab misses
    (unknown namespaces/objects/users)."""
    reqs = []
    for _ in range(k):
        miss = rng.random() < 0.2
        ns = "missing-ns" if miss else _NAMESPACES[rng.integers(3)]
        obj = f"o{rng.integers(14)}"
        rel = _RELATIONS[rng.integers(3)]
        if rng.random() < 0.3:
            subject = SubjectSet(
                namespace=_NAMESPACES[rng.integers(3)],
                object=f"o{rng.integers(14)}",
                relation=_RELATIONS[rng.integers(3)],
            )
        else:
            subject = SubjectID(id=f"u{rng.integers(24)}")
        reqs.append(
            RelationTuple(
                namespace=ns, object=obj, relation=rel, subject=subject
            )
        )
    return reqs


class TestCheckColumns:
    def test_from_tuples_materialize_roundtrip(self):
        reqs = [
            _t("n:doc0#view@alice"),
            _t("ns-日本語:doc1#edit@(grüße:team0#member)"),
        ]
        cols = CheckColumns.from_tuples(reqs)
        assert len(cols) == 2
        assert cols.materialize() == reqs
        assert cols.is_id_rows() == [True, False]
        assert cols.start_keys()[1] == ("ns-日本語", "doc1", "edit")
        assert cols.target_keys() == [
            ("alice",),
            ("grüße", "team0", "member"),
        ]

    def test_validate_normalizes_omitted_subject_columns(self):
        cols = CheckColumns(
            ["n", "n"], ["o1", "o2"], ["view", "view"],
            subject_ids=["alice", "bob"],
        ).validate()
        assert cols.subject_set_namespaces == ["", ""]
        assert cols.materialize()[0].subject == SubjectID(id="alice")

    def test_row_without_subject_rejected(self):
        with pytest.raises(ErrMalformedInput, match="without subject"):
            CheckColumns(
                ["n"], ["o"], ["view"], subject_ids=[""]
            ).validate()

    def test_row_with_both_subject_forms_rejected(self):
        with pytest.raises(ErrMalformedInput, match="both subject_id"):
            CheckColumns(
                ["n"], ["o"], ["view"],
                subject_ids=["alice"],
                subject_set_namespaces=["n"],
                subject_set_objects=["g"],
                subject_set_relations=["member"],
            ).validate()

    def test_length_mismatch_rejected(self):
        with pytest.raises(ErrMalformedInput, match="length mismatch"):
            CheckColumns(["n", "n"], ["o"], ["view", "view"]).validate()
        with pytest.raises(ErrMalformedInput, match="length mismatch"):
            CheckColumns(
                ["n", "n"], ["o1", "o2"], ["view", "view"],
                subject_ids=["alice"],
            ).validate()

    def test_rest_body_type_guard(self):
        with pytest.raises(ErrMalformedInput, match="array of strings"):
            CheckColumns.from_rest_body(
                {"namespaces": "n", "objects": ["o"], "relations": ["v"]}
            )
        with pytest.raises(ErrMalformedInput, match="array of strings"):
            CheckColumns.from_rest_body(
                {
                    "namespaces": ["n"],
                    "objects": [1],
                    "relations": ["v"],
                    "subject_ids": ["a"],
                }
            )

    def test_select_keeps_parallel_rows(self):
        reqs = [_t(f"n:o{i}#view@u{i}") for i in range(5)]
        cols = CheckColumns.from_tuples(reqs)
        sub = cols.select([0, 3])
        assert sub.materialize() == [reqs[0], reqs[3]]


class TestEngineParityFuzz:
    @pytest.mark.parametrize("seed", [3, 11, 29])
    def test_columnar_paths_match_oracle(self, seed):
        rng = np.random.default_rng(seed)
        store = _random_store(rng)
        snaps = SnapshotManager(store)
        oracle = CheckEngine(store, max_depth=5)
        device = DeviceCheckEngine(snaps, max_depth=5)
        closure = ClosureCheckEngine(snaps, max_depth=5)
        batcher = CheckBatcher(
            device, window_s=0, encoded_cache_size=512
        )
        try:
            for _round in range(3):
                reqs = _random_requests(rng, 48)
                want = [
                    bool(oracle.subject_is_allowed(r, 5)) for r in reqs
                ]
                cols = CheckColumns.from_tuples(reqs)
                # per-tuple convert path (the pre-existing contract)
                assert [
                    bool(v) for v in device.batch_check(reqs)
                ] == want
                # engine-level columnar
                for eng in (device, closure):
                    got = eng.batch_check_columns(cols)
                    assert [bool(v) for v in got] == want, type(eng)
                # batcher columnar (twice: second round rides the caches)
                for _ in range(2):
                    got = batcher.check_batch_columnar(cols)
                    assert [bool(v) for v in got] == want
                # pre-encoded id path
                snap = snaps.snapshot()
                s_ids, t_ids = snap.encode_requests_columnar(cols)
                for _ in range(2):
                    got = batcher.check_batch_encoded(s_ids, t_ids)
                    assert [bool(v) for v in got] == want
        finally:
            batcher.close()

    def test_closure_batcher_parity(self):
        """The serial engine path (row_keys cache, no encode/launch
        split) must agree with the oracle too."""
        rng = np.random.default_rng(5)
        store = _random_store(rng)
        snaps = SnapshotManager(store)
        oracle = CheckEngine(store, max_depth=5)
        from keto_tpu.engine.cache import CheckResultCache

        store_ref = store
        batcher = CheckBatcher(
            ClosureCheckEngine(snaps, max_depth=5), window_s=0,
            cache=CheckResultCache(256),
            version_fn=lambda: store_ref.version,
        )
        try:
            reqs = _random_requests(rng, 40)
            want = [bool(oracle.subject_is_allowed(r, 5)) for r in reqs]
            cols = CheckColumns.from_tuples(reqs)
            for _ in range(2):
                got = batcher.check_batch_columnar(cols)
                assert [bool(v) for v in got] == want
        finally:
            batcher.close()


class TestBreakerFallbackParity:
    """PR-1 failure semantics preserved: with the circuit open, columnar
    and encoded batches are re-answered by the host oracle from lazily
    materialized tuples — identical answers, no per-tuple objects on the
    healthy path."""

    @pytest.fixture(autouse=True)
    def _clean_faults(self):
        FAULTS.reset()
        yield
        FAULTS.reset()

    def _fixture(self):
        rng = np.random.default_rng(17)
        store = _random_store(rng)
        snaps = SnapshotManager(store)
        fb = DeviceFallbackEngine(
            DeviceCheckEngine(snaps, max_depth=5),
            lambda: CheckEngine(store, max_depth=5),
            failure_threshold=1,
            cooldown_s=60.0,
        )
        oracle = CheckEngine(store, max_depth=5)
        reqs = _random_requests(rng, 32)
        want = [bool(oracle.subject_is_allowed(r, 5)) for r in reqs]
        return snaps, fb, reqs, want

    def test_columnar_fallback_on_raise(self):
        snaps, fb, reqs, want = self._fixture()
        cols = CheckColumns.from_tuples(reqs)
        assert fb.batch_check_columns(cols) == want  # healthy
        FAULTS.arm("device.compile_error", times=1)
        assert fb.batch_check_columns(cols) == want  # trip + re-answer
        assert fb.circuit_open()
        assert fb.batch_check_columns(cols) == want  # open: oracle serves

    def test_batcher_columnar_and_encoded_fallback(self):
        snaps, fb, reqs, want = self._fixture()
        cols = CheckColumns.from_tuples(reqs)
        b = CheckBatcher(fb, window_s=0, encoded_cache_size=0)
        try:
            assert [bool(v) for v in b.check_batch_columnar(cols)] == want
            FAULTS.arm("device.compile_error", times=1)
            got = b.check_batch_columnar(cols)
            assert [bool(v) for v in got] == want
            assert fb.circuit_open()
            # pure-id encoded batches while open: tuples decoded from the
            # snapshot vocab before the oracle re-answers
            snap = snaps.snapshot()
            s_ids, t_ids = snap.encode_requests_columnar(cols)
            got = b.check_batch_encoded(s_ids, t_ids)
            assert [bool(v) for v in got] == want
        finally:
            b.close()

    def test_encoded_garbage_batch_reanswered(self):
        snaps, fb, reqs, want = self._fixture()
        cols = CheckColumns.from_tuples(reqs)
        b = CheckBatcher(fb, window_s=0, encoded_cache_size=0)
        try:
            snap = snaps.snapshot()
            s_ids, t_ids = snap.encode_requests_columnar(cols)
            assert [bool(v) for v in b.check_batch_encoded(s_ids, t_ids)] == want
            FAULTS.arm("device.batch_nan", times=1)
            got = b.check_batch_encoded(s_ids, t_ids)
            assert [bool(v) for v in got] == want
        finally:
            b.close()


class TestEncodedCacheFreshness:
    def test_cache_does_not_serve_stale_answers_across_writes(self):
        store = InMemoryTupleStore()
        store.write_relation_tuples(
            _t("n:doc0#view@(n:team0#member)"),
            _t("n:team0#member@alice"),
        )
        snaps = SnapshotManager(store)
        engine = DeviceCheckEngine(snaps, max_depth=5)
        b = CheckBatcher(engine, window_s=0, encoded_cache_size=512)
        try:
            cols = CheckColumns.from_tuples(
                [_t("n:doc0#view@alice"), _t("n:doc0#view@bob")]
            )
            assert [bool(v) for v in b.check_batch_columnar(cols)] == [
                True, False,
            ]
            store.write_relation_tuples(_t("n:team0#member@bob"))
            got = b.check_batch_columnar(
                cols, min_version=store.version
            )
            assert [bool(v) for v in got] == [True, True]
            store.delete_relation_tuples(_t("n:team0#member@alice"))
            got = b.check_batch_columnar(
                cols, min_version=store.version
            )
            assert [bool(v) for v in got] == [False, True]
        finally:
            b.close()


# ---------------------------------------------------------------------------
# live-server transports
# ---------------------------------------------------------------------------


class _ServerFixture:
    def __init__(self, config: Config):
        self.registry = Registry(config)
        self.loop = asyncio.new_event_loop()
        self.thread = threading.Thread(
            target=self.loop.run_forever, daemon=True
        )
        self.thread.start()
        fut = asyncio.run_coroutine_threadsafe(
            self.registry.start_all(), self.loop
        )
        self.read_port, self.write_port = fut.result(timeout=180)

    def stop(self):
        asyncio.run_coroutine_threadsafe(
            self.registry.stop_all(), self.loop
        ).result(timeout=10)
        self.loop.call_soon_threadsafe(self.loop.stop)
        self.thread.join(timeout=5)


@pytest.fixture(scope="module")
def server():
    cfg = Config(
        values={
            "namespaces": [
                {"id": 1, "name": "n"},
                {"id": 2, "name": "ns-日本語"},
            ],
            "log": {"level": "error"},
            "serve": {
                "read": {"port": 0, "host": "127.0.0.1"},
                "write": {"port": 0, "host": "127.0.0.1"},
            },
        }
    )
    s = _ServerFixture(cfg)
    store = s.registry.store()
    store.write_relation_tuples(
        _t("n:doc0#view@(n:team0#member)"),
        _t("n:team0#member@alice"),
        _t("n:doc1#view@bob"),
        _t("ns-日本語:ページ#view@ユーザー"),
    )
    yield s
    s.stop()


def _columnar_body(reqs):
    cols = CheckColumns.from_tuples(reqs)
    return {
        "namespaces": cols.namespaces,
        "objects": cols.objects,
        "relations": cols.relations,
        "subject_ids": cols.subject_ids,
        "subject_set_namespaces": cols.subject_set_namespaces,
        "subject_set_objects": cols.subject_set_objects,
        "subject_set_relations": cols.subject_set_relations,
    }


_SERVER_REQS = [
    "n:doc0#view@alice",
    "n:doc0#view@bob",
    "n:doc1#view@bob",
    "n:doc0#view@(n:team0#member)",
    "ns-日本語:ページ#view@ユーザー",
    "ns-日本語:ページ#view@alice",
]


class TestRestColumnar:
    def test_columnar_body_matches_per_tuple(self, server):
        reqs = [_t(s) for s in _SERVER_REQS]
        with httpx.Client(
            base_url=f"http://127.0.0.1:{server.read_port}", timeout=60
        ) as c:
            per_tuple = c.post(
                "/check/batch",
                json={"tuples": [t.to_dict() for t in reqs]},
            )
            assert per_tuple.status_code == 200
            want = per_tuple.json()["allowed"]
            assert want == [True, False, True, True, True, False]
            columnar = c.post("/check/batch", json=_columnar_body(reqs))
            assert columnar.status_code == 200
            body = columnar.json()
            assert body["allowed"] == want
            assert body["snaptoken"]

    def test_columnar_body_without_set_columns(self, server):
        with httpx.Client(
            base_url=f"http://127.0.0.1:{server.read_port}", timeout=60
        ) as c:
            r = c.post(
                "/check/batch",
                json={
                    "namespaces": ["n", "n"],
                    "objects": ["doc0", "doc1"],
                    "relations": ["view", "view"],
                    "subject_ids": ["alice", "bob"],
                },
            )
            assert r.status_code == 200
            assert r.json()["allowed"] == [True, True]

    def test_malformed_columnar_bodies_400(self, server):
        cases = [
            # row without any subject
            {
                "namespaces": ["n"], "objects": ["doc0"],
                "relations": ["view"], "subject_ids": [""],
            },
            # both subject forms on one row
            {
                "namespaces": ["n"], "objects": ["doc0"],
                "relations": ["view"], "subject_ids": ["alice"],
                "subject_set_namespaces": ["n"],
                "subject_set_objects": ["team0"],
                "subject_set_relations": ["member"],
            },
            # column length mismatch
            {
                "namespaces": ["n", "n"], "objects": ["doc0"],
                "relations": ["view", "view"],
                "subject_ids": ["alice", "bob"],
            },
            # wrong element type
            {
                "namespaces": ["n"], "objects": [7],
                "relations": ["view"], "subject_ids": ["alice"],
            },
        ]
        with httpx.Client(
            base_url=f"http://127.0.0.1:{server.read_port}", timeout=60
        ) as c:
            for body in cases:
                r = c.post("/check/batch", json=body)
                assert r.status_code == 400, body
                assert "error" in r.json()


class TestGrpcColumnar:
    def _stub(self, server):
        ch = grpc.insecure_channel(f"127.0.0.1:{server.read_port}")
        return ch, CheckServiceStub(ch)

    def test_columnar_request_matches_per_tuple(self, server):
        reqs = [_t(s) for s in _SERVER_REQS]
        per_tuple = check_service_pb2.BatchCheckRequest(
            tuples=[
                check_service_pb2.CheckRequestTuple(
                    namespace=t.namespace,
                    object=t.object,
                    relation=t.relation,
                    subject=acl_pb2.Subject(id=t.subject.id)
                    if isinstance(t.subject, SubjectID)
                    else acl_pb2.Subject(
                        set=acl_pb2.SubjectSet(
                            namespace=t.subject.namespace,
                            object=t.subject.object,
                            relation=t.subject.relation,
                        )
                    ),
                )
                for t in reqs
            ]
        )
        cols = CheckColumns.from_tuples(reqs)
        columnar = check_service_pb2.BatchCheckRequest(
            namespaces=cols.namespaces,
            objects=cols.objects,
            relations=cols.relations,
            subject_ids=cols.subject_ids,
            subject_set_namespaces=cols.subject_set_namespaces,
            subject_set_objects=cols.subject_set_objects,
            subject_set_relations=cols.subject_set_relations,
        )
        ch, stub = self._stub(server)
        try:
            want = list(stub.BatchCheck(per_tuple).allowed)
            assert want == [True, False, True, True, True, False]
            resp = stub.BatchCheck(columnar)
            assert list(resp.allowed) == want
            assert resp.snaptoken
        finally:
            ch.close()

    def test_malformed_columnar_request_invalid_argument(self, server):
        ch, stub = self._stub(server)
        try:
            req = check_service_pb2.BatchCheckRequest(
                namespaces=["n"],
                objects=["doc0"],
                relations=["view"],
                subject_ids=[""],
            )
            with pytest.raises(grpc.RpcError) as exc:
                stub.BatchCheck(req)
            assert exc.value.code() == grpc.StatusCode.INVALID_ARGUMENT
            assert "without subject" in exc.value.details()
        finally:
            ch.close()
