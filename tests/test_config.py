"""Config provider regression tests (falsy defaults, env/flag precedence)."""

from keto_tpu.driver.config import Config


class TestConfigDefaults:
    def test_explicit_falsy_defaults_are_honored(self):
        c = Config(values={}, env={})
        # a caller-provided falsy default must not fall through to DEFAULTS
        assert c.get("engine.batch_window_us", default=0) == 0
        assert c.get("serve.read.port", default=0) == 0
        assert c.get("log.level", default="") == ""
        assert c.get("namespaces", default=False) is False

    def test_missing_key_without_default_uses_defaults_table(self):
        c = Config(values={}, env={})
        assert c.get("serve.read.port") == 4466
        assert c.get("engine.mode") == "closure"
        assert c.get("no.such.key") is None

    def test_data_value_wins_over_default(self):
        c = Config(values={"serve": {"read": {"port": 1234}}}, env={})
        assert c.get("serve.read.port", default=0) == 1234

    def test_env_override_wins(self):
        c = Config(values={}, env={"KETO_SERVE_READ_PORT": "9999"})
        assert c.get("serve.read.port", default=0) == 9999

    def test_flag_override_wins_over_env(self):
        c = Config(
            values={},
            env={"KETO_SERVE_READ_PORT": "9999"},
            flag_overrides={"serve.read.port": 1111},
        )
        assert c.get("serve.read.port") == 1111
