"""Config provider regression tests (falsy defaults, env/flag precedence)."""

from keto_tpu.driver.config import Config


class TestConfigDefaults:
    def test_explicit_falsy_defaults_are_honored(self):
        c = Config(values={}, env={})
        # a caller-provided falsy default must not fall through to DEFAULTS
        assert c.get("engine.batch_window_us", default=0) == 0
        assert c.get("serve.read.port", default=0) == 0
        assert c.get("log.level", default="") == ""
        assert c.get("namespaces", default=False) is False

    def test_missing_key_without_default_uses_defaults_table(self):
        c = Config(values={}, env={})
        assert c.get("serve.read.port") == 4466
        assert c.get("engine.mode") == "closure"
        assert c.get("no.such.key") is None

    def test_data_value_wins_over_default(self):
        c = Config(values={"serve": {"read": {"port": 1234}}}, env={})
        assert c.get("serve.read.port", default=0) == 1234

    def test_env_override_wins(self):
        c = Config(values={}, env={"KETO_SERVE_READ_PORT": "9999"})
        assert c.get("serve.read.port", default=0) == 9999

    def test_flag_override_wins_over_env(self):
        c = Config(
            values={},
            env={"KETO_SERVE_READ_PORT": "9999"},
            flag_overrides={"serve.read.port": 1111},
        )
        assert c.get("serve.read.port") == 1111


class TestShardingConfig:
    def test_sharding_defaults(self):
        c = Config(values={}, env={})
        assert c.get("engine.sharding.enabled") is False
        assert c.get("engine.sharding.data") == 1
        assert c.get("engine.sharding.edge") == 0
        assert c.get("engine.sharding.edge_chunk") == 0
        assert c.get("engine.sharding.escalation_budget") == 0.05

    def test_sharding_values_round_trip(self):
        c = Config(
            values={
                "engine": {
                    "sharding": {
                        "enabled": True,
                        "data": 2,
                        "edge": 4,
                        "edge_chunk": 1 << 20,
                        "escalation_budget": 0.01,
                    }
                }
            },
            env={},
        )
        assert c.get("engine.sharding.enabled") is True
        assert c.get("engine.sharding.data") == 2
        assert c.get("engine.sharding.edge") == 4
        assert c.get("engine.sharding.edge_chunk") == 1 << 20
        assert c.get("engine.sharding.escalation_budget") == 0.01

    def test_sharding_env_override(self):
        c = Config(
            values={}, env={"KETO_ENGINE_SHARDING_ENABLED": "true"}
        )
        assert c.get("engine.sharding.enabled") in (True, "true")

    def test_sharding_keys_in_exported_schema(self):
        from keto_tpu.driver.config import CONFIG_SCHEMA

        props = CONFIG_SCHEMA["properties"]["engine"]["properties"]
        sharding = props["sharding"]["properties"]
        assert set(sharding) == {
            "enabled", "data", "edge", "edge_chunk", "escalation_budget"
        }
        # misspelled keys must be rejected, same as every engine block
        assert props["sharding"]["additionalProperties"] is False

    def test_sharding_keys_validate(self):
        import jsonschema
        import pytest
        from keto_tpu.driver.config import CONFIG_SCHEMA

        jsonschema.validate(
            {"engine": {"sharding": {"enabled": True, "data": 2}}},
            CONFIG_SCHEMA,
        )
        with pytest.raises(jsonschema.ValidationError):
            jsonschema.validate(
                {"engine": {"sharding": {"escalation_budget": 2.0}}},
                CONFIG_SCHEMA,
            )
