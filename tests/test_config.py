"""Config provider regression tests (falsy defaults, env/flag precedence)."""

from keto_tpu.driver.config import Config


class TestConfigDefaults:
    def test_explicit_falsy_defaults_are_honored(self):
        c = Config(values={}, env={})
        # a caller-provided falsy default must not fall through to DEFAULTS
        assert c.get("engine.batch_window_us", default=0) == 0
        assert c.get("serve.read.port", default=0) == 0
        assert c.get("log.level", default="") == ""
        assert c.get("namespaces", default=False) is False

    def test_missing_key_without_default_uses_defaults_table(self):
        c = Config(values={}, env={})
        assert c.get("serve.read.port") == 4466
        assert c.get("engine.mode") == "device"
        assert c.get("no.such.key") is None

    def test_data_value_wins_over_default(self):
        c = Config(values={"serve": {"read": {"port": 1234}}}, env={})
        assert c.get("serve.read.port", default=0) == 1234

    def test_env_override_wins(self):
        c = Config(values={}, env={"KETO_SERVE_READ_PORT": "9999"})
        assert c.get("serve.read.port", default=0) == 9999

    def test_flag_override_wins_over_env(self):
        c = Config(
            values={},
            env={"KETO_SERVE_READ_PORT": "9999"},
            flag_overrides={"serve.read.port": 1111},
        )
        assert c.get("serve.read.port") == 1111


class TestShardedBucket:
    def test_bucket_batch_terminates_for_non_power_of_two_data_axis(self):
        from keto_tpu.parallel.sharded import ShardedCheckEngine

        class Dummy:
            pass

        for n_data in (1, 2, 3, 5, 6, 7, 8):
            eng = Dummy()
            eng.n_data = n_data
            for n in (1, 7, 8, 9, 100, 4096):
                b = ShardedCheckEngine._bucket_batch(eng, n)
                assert b >= n
                assert b % n_data == 0
                per = b // n_data
                assert per & (per - 1) == 0  # per-device slice is a pow2
