"""Docs-code-samples harness: the documentation must execute.

The reference runs its docs snippets in CI (reference Makefile:100-105,
contrib/docs-code-samples/); the analog here:

- every ```python block in docs/**/*.md runs, blocks sharing one
  namespace per file (so a page can build on earlier snippets);
- every untagged ``` block's tuple-looking lines must parse with
  RelationTuple.from_string — the tuple grammar shown in the concepts
  pages cannot drift from the parser.
"""

import os
import re

import pytest

from keto_tpu.relationtuple import RelationTuple

DOCS_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "docs"
)

_FENCE = re.compile(r"^```(\w*)\s*$")


def _blocks(path: str):
    """(lang, text, lineno) for every fenced block in a markdown file."""
    out = []
    lang = None
    buf: list[str] = []
    start = 0
    with open(path) as f:
        for i, line in enumerate(f, 1):
            m = _FENCE.match(line)
            if m is None:
                if lang is not None:
                    buf.append(line)
                continue
            if lang is None:
                lang = m.group(1)
                buf = []
                start = i
            else:
                out.append((lang, "".join(buf), start))
                lang = None
    return out


def _doc_files():
    for root, _dirs, files in os.walk(DOCS_DIR):
        for f in sorted(files):
            if f.endswith(".md"):
                yield os.path.join(root, f)


DOC_FILES = list(_doc_files())
assert DOC_FILES, "docs/ disappeared?"


def _rel(path):
    return os.path.relpath(path, DOCS_DIR)


@pytest.mark.parametrize("path", DOC_FILES, ids=_rel)
def test_python_blocks_execute(path):
    blocks = [b for b in _blocks(path) if b[0] == "python"]
    # pages without python blocks vacuously pass (a skip would read as a
    # gap in the default run's zero-skip contract)
    ns: dict = {}
    for _lang, text, lineno in blocks:
        code = compile(text, f"{_rel(path)}:{lineno}", "exec")
        exec(code, ns)  # noqa: S102 - that's the point of the harness


_TUPLE_LINE = re.compile(r"^[^\s#@]\S*:\S.*#.*@")


@pytest.mark.parametrize("path", DOC_FILES, ids=_rel)
def test_tuple_grammar_blocks_parse(path):
    checked = 0
    for lang, text, lineno in _blocks(path):
        if lang:  # only untagged grammar blocks
            continue
        for line in text.splitlines():
            # strip trailing prose comments ("...   # explanation")
            candidate = re.split(r"\s{2,}#", line.strip(), maxsplit=1)[0]
            if not candidate or not _TUPLE_LINE.match(candidate):
                continue
            RelationTuple.from_string(candidate)  # raises on drift
            checked += 1
    # pages without tuple-grammar lines vacuously pass
