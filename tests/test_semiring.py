"""Semiring closure builder parity + incremental correctness.

The contract (keto_tpu/engine/semiring.py): the bitset masked-SpMV builder
and the incremental dirty-row updater produce byte-identical uint8 closure
matrices to the legacy dense-matmul builder (ops.closure.build_closure_packed)
on every graph — cycles, unicode vocab, padding, arbitrary insert/delete
deltas, and snapshot-overlay rebuilds mid-serve included.
"""

import numpy as np
import pytest

from keto_tpu.engine.closure import ClosureCheckEngine
from keto_tpu.engine.semiring import (
    build_closure_bitset,
    interior_edge_delta,
    update_closure_bitset,
)
from keto_tpu.graph import SnapshotManager
from keto_tpu.graph.interior import build_interior, interior_blocks
from keto_tpu.ops.closure import build_closure_packed, pack_adjacency
from keto_tpu.relationtuple import RelationTuple, SubjectSet
from keto_tpu.store import InMemoryTupleStore

from test_closure_engine import _random_requests
from test_device_engines import random_store


def t(s: str) -> RelationTuple:
    return RelationTuple.from_string(s)


def _m_pad(m):
    return ((m + 255) // 256) * 256


def _rand_edges(rng, m, n_edges):
    src = rng.integers(0, m, n_edges, dtype=np.int32)
    dst = rng.integers(0, m, n_edges, dtype=np.int32)
    return src, dst


def _oracle(src, dst, m, m_pad, k_max):
    packed = pack_adjacency(src, dst, m_pad)
    return np.asarray(build_closure_packed(packed, m, m_pad=m_pad, k_max=k_max))


class TestBitsetParity:
    def test_matches_matmul_on_random_graphs(self):
        rng = np.random.default_rng(3)
        for _ in range(25):
            m = int(rng.integers(0, 60))
            m_pad = _m_pad(m)
            n_edges = int(rng.integers(0, 4 * max(m, 1)))
            src, dst = _rand_edges(rng, max(m, 1), n_edges)
            if m == 0:
                src = src[:0]
                dst = dst[:0]
            k_max = int(rng.integers(1, 7))
            got = build_closure_bitset(src, dst, m, m_pad, k_max)
            want = _oracle(src, dst, m, m_pad, k_max)
            np.testing.assert_array_equal(got, want)

    def test_cycles_and_self_loops(self):
        # 0 -> 1 -> 2 -> 0 cycle plus a self loop: distances clamp at
        # k_max, diagonal stays 0 (a cycle never shrinks it)
        src = np.array([0, 1, 2, 3], dtype=np.int32)
        dst = np.array([1, 2, 0, 3], dtype=np.int32)
        for k_max in (1, 2, 3, 6):
            got = build_closure_bitset(src, dst, 4, 256, k_max)
            want = _oracle(src, dst, 4, 256, k_max)
            np.testing.assert_array_equal(got, want)

    def test_block_scheduled_and_threaded(self):
        rng = np.random.default_rng(5)
        for _ in range(8):
            m = int(rng.integers(10, 80))
            m_pad = _m_pad(m)
            src, dst = _rand_edges(rng, m, 3 * m)

            class _IG:
                pass

            ig = _IG()
            ig.m = m
            ig.ii_src = src
            ig.ii_dst = dst
            blocks = interior_blocks(ig)
            got = build_closure_bitset(
                src, dst, m, m_pad, 4, workers=4, blocks=blocks
            )
            want = _oracle(src, dst, m, m_pad, 4)
            np.testing.assert_array_equal(got, want)

    def test_padding_rows_stay_inf(self):
        src = np.array([0], dtype=np.int32)
        dst = np.array([1], dtype=np.int32)
        d = build_closure_bitset(src, dst, 2, 256, 4)
        assert (d[2:] == 255).all()
        assert d[0, 0] == 0 and d[1, 1] == 0
        assert d[0, 1] == 1


class TestIncremental:
    def test_insert_and_delete_deltas(self):
        rng = np.random.default_rng(9)
        for trial in range(20):
            m = int(rng.integers(8, 64))
            m_pad = _m_pad(m)
            src, dst = _rand_edges(rng, m, 3 * m)
            k_max = int(rng.integers(2, 6))
            d_prev = build_closure_bitset(src, dst, m, m_pad, k_max)
            # arbitrary delta: drop a slice, add fresh edges
            keep = rng.random(len(src)) > 0.2
            add_src, add_dst = _rand_edges(rng, m, int(rng.integers(1, 10)))
            new_src = np.concatenate([src[keep], add_src])
            new_dst = np.concatenate([dst[keep], add_dst])
            d_new, n_dirty = update_closure_bitset(
                d_prev, src, dst, new_src, new_dst, m, m_pad, k_max
            )
            want = build_closure_bitset(new_src, new_dst, m, m_pad, k_max)
            np.testing.assert_array_equal(d_new, want, err_msg=f"trial {trial}")
            assert n_dirty <= m

    def test_deletion_only_with_block_refinement(self):
        rng = np.random.default_rng(13)
        for _ in range(10):
            m = int(rng.integers(8, 64))
            m_pad = _m_pad(m)
            src, dst = _rand_edges(rng, m, 3 * m)

            class _IG:
                pass

            ig = _IG()
            ig.m = m
            ig.ii_src = src
            ig.ii_dst = dst
            blocks = interior_blocks(ig)
            d_prev = build_closure_bitset(src, dst, m, m_pad, 4)
            keep = rng.random(len(src)) > 0.3
            d_new, _ = update_closure_bitset(
                d_prev,
                src,
                dst,
                src[keep],
                dst[keep],
                m,
                m_pad,
                4,
                blocks=blocks,
            )
            want = build_closure_bitset(src[keep], dst[keep], m, m_pad, 4)
            np.testing.assert_array_equal(d_new, want)

    def test_empty_delta_reuses_matrix(self):
        src = np.array([0, 1], dtype=np.int32)
        dst = np.array([1, 2], dtype=np.int32)
        d = build_closure_bitset(src, dst, 3, 256, 4)
        # same edges, different order/duplicates: no dirty rows at all
        src2 = np.array([1, 0, 0], dtype=np.int32)
        dst2 = np.array([2, 1, 1], dtype=np.int32)
        d_new, n_dirty = update_closure_bitset(
            d, src, dst, src2, dst2, 3, 256, 4
        )
        assert n_dirty == 0
        assert d_new is d

    def test_edge_delta_keys(self):
        ins, dele = interior_edge_delta(
            np.array([0, 1]),
            np.array([1, 2]),
            np.array([1, 5]),
            np.array([2, 6]),
            256,
        )
        assert list(ins) == [5 * 256 + 6]
        assert list(dele) == [0 * 256 + 1]


class TestEngineParity:
    """ClosureCheckEngine(builder=semiring) vs builder=matmul vs the
    host-recursion oracle, over random stores with unicode vocab and
    overlay deltas applied mid-serve."""

    def _engines(self, store, **kw):
        from keto_tpu.engine import CheckEngine

        # strong freshness + no debounce: every write's rebuild happens
        # synchronously inside the next batch_check, so the build-path
        # counters below observe it deterministically
        kw.setdefault("freshness", "strong")
        kw.setdefault("rebuild_debounce_s", 0.0)
        oracle = CheckEngine(store, max_depth=5)
        semi = ClosureCheckEngine(
            SnapshotManager(store), max_depth=5, builder="semiring", **kw
        )
        mat = ClosureCheckEngine(
            SnapshotManager(store), max_depth=5, builder="matmul", **kw
        )
        return oracle, semi, mat

    def test_random_graph_parity(self):
        rng = np.random.default_rng(21)
        store = random_store(rng, n_objects=40, n_users=30, n_edges=300)
        oracle, semi, mat = self._engines(store)
        reqs = _random_requests(rng, 40, 30, k=128)
        want = oracle.batch_check(reqs)
        assert semi.batch_check(reqs) == want
        assert mat.batch_check(reqs) == want
        assert semi.last_build_phases.get("kernel") is not None
        assert semi.last_build_phases.get("blocks") is not None

    def test_unicode_vocab(self):
        store = InMemoryTupleStore()
        store.write_relation_tuples(
            t("n:café#члены@(n:日本語#члены)"),
            t("n:日本語#члены@(ユーザー☃)"),
            t("n:café#viewer@(n:café#члены)"),
        )
        oracle, semi, mat = self._engines(store)
        reqs = [
            t("n:café#viewer@(ユーザー☃)"),
            t("n:café#члены@(ユーザー☃)"),
            t("n:café#viewer@(nobody)"),
        ]
        want = oracle.batch_check(reqs)
        assert want == [True, True, False]
        assert semi.batch_check(reqs) == want
        assert mat.batch_check(reqs) == want

    def test_overlay_delta_mid_serve_goes_incremental(self):
        """A write burst past the 8-edge patch window, landing entirely on
        already-interior nodes, takes the semiring dirty-row rebuild (no
        full-rebuild cliff) and stays exact."""
        store = InMemoryTupleStore()
        base = [t(f"n:root#r@(n:g{i}#m)") for i in range(12)]
        base += [t(f"n:g{i}#m@(u{i})") for i in range(12)]
        base.append(t("n:top#r@(n:root#r)"))
        store.write_relation_tuples(*base)
        oracle, semi, mat = self._engines(store)
        reqs = [t(f"n:root#r@(u{i})") for i in range(12)]
        reqs += [t(f"n:g0#m@(u{i})") for i in range(12)]
        assert semi.batch_check(reqs) == oracle.batch_check(reqs)
        assert semi.n_incremental_builds == 0
        # 12 fresh set->set edges between EXISTING interior nodes: blows
        # the per-edge patch window, keeps the interior node set stable —
        # the write overlay serves it exactly, and the COMPACTION rebuild
        # (folding the overlay back into D) must take the semiring
        # dirty-row path, not the full O(m^3) build the old engine re-ran
        burst = [t(f"n:g{i}#m@(n:g{(i + 1) % 12}#m)") for i in range(12)]
        store.write_relation_tuples(*burst)
        want = oracle.batch_check(reqs)
        assert semi.batch_check(reqs) == want
        assert mat.batch_check(reqs) == want
        full0 = semi.n_full_builds
        semi._build_sync()  # the overlay-compaction rebuild, on demand
        assert semi.n_incremental_builds >= 1
        assert semi.n_full_builds == full0
        assert semi.last_build_phases.get("incremental") is not None
        # the compacted closure must still answer exactly
        assert semi.batch_check(reqs) == want

    def test_deletion_goes_incremental(self):
        """Deletions force a snapshot re-encode; on a store with a stable
        append-only vocab (columnar) and an unchanged interior node set,
        the engine still updates D incrementally instead of rebuilding."""
        from keto_tpu.store.columnar import ColumnarTupleStore

        store = ColumnarTupleStore()
        base = [t(f"n:top#r@(n:p{i}#r)") for i in range(2)]
        base += [t(f"n:p{i}#r@(n:s#m)") for i in range(2)]
        base += [t("n:s#m@(u1)"), t("n:keep#r@(n:s#m)")]
        store.write_relation_tuples(*base)
        oracle, semi, _ = self._engines(store)
        reqs = [
            t("n:top#r@(u1)"),
            t("n:p0#r@(u1)"),
            t("n:p1#r@(u1)"),
            t("n:s#m@(u1)"),
            t("n:top#r@(u2)"),
        ]
        assert semi.batch_check(reqs) == oracle.batch_check(reqs)
        # delete an interior-interior edge; s#m keeps other incoming
        # edges so the interior node set is unchanged
        store.delete_relation_tuples(t("n:p1#r@(n:s#m)"))
        want = oracle.batch_check(reqs)
        assert want == [True, True, False, True, False]
        assert semi.batch_check(reqs) == want
        full0 = semi.n_full_builds
        semi._build_sync()  # fold the deletion into D: incremental path
        assert semi.n_incremental_builds >= 1
        assert semi.n_full_builds == full0
        assert semi.batch_check(reqs) == want

    def test_builder_knob_validation(self):
        store = InMemoryTupleStore()
        with pytest.raises(ValueError):
            ClosureCheckEngine(SnapshotManager(store), builder="nope")
