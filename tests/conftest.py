"""Test configuration.

Backend note: on this machine an axon sitecustomize imports jax at
interpreter start and pins the single real TPU chip — env tweaks here can no
longer change the backend, so the main suite runs on whatever the
interpreter started with (TPU under axon, CPU elsewhere). Multi-device
sharding tests (test_multichip_sharded.py) need an 8-device CPU mesh and are
driven through a subprocess with
``PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu
XLA_FLAGS=--xla_force_host_platform_device_count=8`` set at interpreter
start (see test_sharded_subprocess.py).
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import pytest  # noqa: E402

from keto_tpu.namespace import MemoryNamespaceManager  # noqa: E402
from keto_tpu.store import InMemoryTupleStore  # noqa: E402


@pytest.fixture
def nsmgr():
    return MemoryNamespaceManager()


@pytest.fixture(params=["memory", "sqlite", "columnar", "postgres"])
def store(request, nsmgr, tmp_path):
    """Every contract/engine test runs against all persistence backends —
    the reference's one-suite-many-DSNs matrix (SURVEY.md §4). The postgres
    leg runs only when KETO_TEST_PG_DSN points at a live server AND a
    psycopg driver exists (the reference's equivalent: -short skips its
    dockertest engines, internal/x/dbx/dsn_testutils.go:36-43)."""
    if request.param == "memory":
        yield InMemoryTupleStore(namespace_manager=nsmgr)
        return
    if request.param == "columnar":
        from keto_tpu.store import ColumnarTupleStore

        yield ColumnarTupleStore(namespace_manager=nsmgr)
        return
    if request.param == "postgres":
        dsn = os.environ.get("KETO_TEST_PG_DSN")
        if not dsn:
            pytest.skip("postgres: set KETO_TEST_PG_DSN to run")
        from keto_tpu.persistence.postgres import PostgresTupleStore

        try:
            s = PostgresTupleStore(dsn, namespace_manager=nsmgr)
        except Exception as e:
            # no driver (RuntimeError) or unreachable server (driver's
            # OperationalError): a visible skip, not a matrix-wide error
            pytest.skip(f"postgres backend unavailable: {e}")
        yield s
        from keto_tpu.relationtuple import RelationQuery

        s.delete_all_relation_tuples(RelationQuery())
        s.close()
        return
    from keto_tpu.persistence import SQLiteTupleStore

    s = SQLiteTupleStore(
        str(tmp_path / "keto.db"), namespace_manager=nsmgr
    )
    yield s
    s.close()
