"""Test configuration.

Backend note: on this machine an axon sitecustomize imports jax at
interpreter start and pins the single real TPU chip — env tweaks here can no
longer change the backend, so the main suite runs on whatever the
interpreter started with (TPU under axon, CPU elsewhere). Multi-device
sharding tests (test_multichip_sharded.py) need an 8-device CPU mesh and are
driven through a subprocess with
``PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu
XLA_FLAGS=--xla_force_host_platform_device_count=8`` set at interpreter
start (see test_sharded_subprocess.py).
"""

import os
import sys


def pytest_configure(config):
    """Re-exec pytest ONCE with a clean hermetic env when the axon TPU
    plugin was registered at interpreter start (PALLAS_AXON_POOL_IPS set).
    On a sick tunneled chip any jax backend touch HANGS instead of raising
    and wedges the whole suite (VERDICT r4); env mutation after interpreter
    start cannot undo the registration, so a fresh exec with axon skipped +
    CPU platform + 8 virtual devices is the only reliable fix. Runs in
    pytest_configure (not at import) so global FD capture can be stopped
    first — exec'ing mid-capture sends the new process's output into
    pytest's about-to-vanish capture temp files."""
    if not (
        os.environ.get("PALLAS_AXON_POOL_IPS")
        and os.environ.get("KETO_TEST_REEXEC") != "1"
    ):
        return
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, repo_root)
    from __graft_entry__ import virtual_cpu_mesh_env

    env = virtual_cpu_mesh_env(8)
    env["KETO_TEST_REEXEC"] = "1"
    capman = config.pluginmanager.getplugin("capturemanager")
    if capman is not None:
        capman.stop_global_capturing()
    sys.stdout.flush()
    sys.stderr.flush()
    os.execve(
        sys.executable,
        [sys.executable, "-m", "pytest", *sys.argv[1:]],
        env,
    )


os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import pytest  # noqa: E402

from keto_tpu.namespace import MemoryNamespaceManager  # noqa: E402
from keto_tpu.store import InMemoryTupleStore  # noqa: E402


@pytest.fixture
def nsmgr():
    return MemoryNamespaceManager()


@pytest.fixture(scope="session")
def pgfake_server():
    """One in-tree fake postgres (wire protocol over sqlite) per session;
    each test leg opens its own logical database on it."""
    from keto_tpu.persistence.pgfake import start_server

    srv = start_server()
    yield srv
    srv.stop()


@pytest.fixture(
    params=["memory", "sqlite", "columnar", "postgres", "mysql", "cockroach"]
)
def store(request, nsmgr, tmp_path):
    """Every contract/engine test runs against all persistence backends —
    the reference's one-suite-many-DSNs matrix across 4 SQL engines
    (SURVEY.md §4; internal/persistence/sql/persister.go:50-51). The
    postgres/cockroach legs speak the real v3 wire protocol through the
    in-tree driver (pgwire.py) — against a live server when
    KETO_TEST_PG_DSN is set, else against the in-tree fake (pgfake.py),
    the same role the reference's dockertest containers play
    (internal/x/dbx/dsn_testutils.go:45-61). The mysql leg runs the MySQL
    dialect SQL through the DB-API translation shim (mysqlfake.py) unless
    KETO_TEST_MYSQL_DSN points at a real server."""
    import uuid as _uuid

    if request.param == "memory":
        yield InMemoryTupleStore(namespace_manager=nsmgr)
        return
    if request.param == "columnar":
        from keto_tpu.store import ColumnarTupleStore

        yield ColumnarTupleStore(namespace_manager=nsmgr)
        return
    if request.param == "postgres":
        from keto_tpu.persistence.postgres import PostgresTupleStore

        dsn = os.environ.get("KETO_TEST_PG_DSN")
        fresh = dsn is None
        if fresh:
            srv = request.getfixturevalue("pgfake_server")
            dsn = (
                f"postgres://keto@127.0.0.1:{srv.port}"
                f"/pg_{_uuid.uuid4().hex[:12]}"
            )
            s = PostgresTupleStore(dsn, namespace_manager=nsmgr)
        else:
            try:
                s = PostgresTupleStore(dsn, namespace_manager=nsmgr)
            except Exception as e:
                # an unreachable EXTERNAL server is a visible skip, not a
                # matrix-wide error (the in-tree fake leg always runs)
                pytest.skip(f"external postgres unavailable: {e}")
        yield s
        if not fresh:  # shared external database: leave it clean
            from keto_tpu.relationtuple import RelationQuery

            s.delete_all_relation_tuples(RelationQuery())
        s.close()
        return
    if request.param == "cockroach":
        from keto_tpu.persistence.dialect import CockroachDialect
        from keto_tpu.persistence.sqlstore import SQLTupleStore

        srv = request.getfixturevalue("pgfake_server")
        s = SQLTupleStore(
            CockroachDialect(),
            f"postgres://keto@127.0.0.1:{srv.port}"
            f"/crdb_{_uuid.uuid4().hex[:12]}",
            namespace_manager=nsmgr,
        )
        yield s
        s.close()
        return
    if request.param == "mysql":
        from keto_tpu.persistence.dialect import MySQLDialect
        from keto_tpu.persistence.sqlstore import SQLTupleStore

        external = os.environ.get("KETO_TEST_MYSQL_DSN")
        dsn = external or f"mysql+fake:///my_{_uuid.uuid4().hex[:12]}"
        try:
            s = SQLTupleStore(MySQLDialect(), dsn, namespace_manager=nsmgr)
        except Exception as e:
            if external:
                pytest.skip(f"external mysql unavailable: {e}")
            raise
        yield s
        if external:  # shared external database: leave it clean
            from keto_tpu.relationtuple import RelationQuery

            s.delete_all_relation_tuples(RelationQuery())
        s.close()
        return
    from keto_tpu.persistence import SQLiteTupleStore

    s = SQLiteTupleStore(
        str(tmp_path / "keto.db"), namespace_manager=nsmgr
    )
    yield s
    s.close()
