"""Test configuration.

Tests run on CPU with 8 virtual devices so multi-chip sharding
(keto_tpu/parallel) is exercised without TPU hardware; set before any jax
import.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import pytest  # noqa: E402

from keto_tpu.namespace import MemoryNamespaceManager  # noqa: E402
from keto_tpu.store import InMemoryTupleStore  # noqa: E402


@pytest.fixture
def nsmgr():
    return MemoryNamespaceManager()


@pytest.fixture(params=["memory", "sqlite"])
def store(request, nsmgr, tmp_path):
    """Every contract/engine test runs against both persistence backends —
    the reference's one-suite-many-DSNs matrix (SURVEY.md §4)."""
    if request.param == "memory":
        yield InMemoryTupleStore(namespace_manager=nsmgr)
        return
    from keto_tpu.persistence import SQLiteTupleStore

    s = SQLiteTupleStore(
        str(tmp_path / "keto.db"), namespace_manager=nsmgr
    )
    yield s
    s.close()
