"""The contrib/cat-videos-example must actually run: serve its keto.yml,
load its tuple files through the CLI, and get the documented answers
(reference contrib/cat-videos-example/ + e2e cases_test.go pattern)."""

import glob
import json
import os

import pytest
from click.testing import CliRunner

from keto_tpu.cli import cli
from keto_tpu.driver import Config
from tests.test_api_server import ServerFixture

EXAMPLE_DIR = os.path.join(
    os.path.dirname(__file__), "..", "contrib", "cat-videos-example"
)


@pytest.fixture(scope="module")
def server():
    cfg = Config(
        config_file=os.path.join(EXAMPLE_DIR, "keto.yml"),
        # free ports instead of the example's canonical 4466/4467
        values={
            "log": {"level": "error"},
            "serve": {
                "read": {"port": 0, "host": "127.0.0.1"},
                "write": {"port": 0, "host": "127.0.0.1"},
            }
        },
        env={},
    )
    s = ServerFixture(cfg)
    yield s
    s.stop()


@pytest.fixture
def runner(server):
    remotes = [
        "--read-remote", f"127.0.0.1:{server.read_port}",
        "--write-remote", f"127.0.0.1:{server.write_port}",
    ]
    return CliRunner(), remotes


def test_example_config_loads_namespaces(server):
    # the example keto.yml (incl. its `version` stamp) must validate and
    # configure the `videos` namespace
    ns = server.registry.namespace_manager().get_namespace_by_name("videos")
    assert ns.name == "videos"


def test_example_end_to_end(runner):
    r, remotes = runner
    files = sorted(glob.glob(os.path.join(EXAMPLE_DIR, "relation-tuples", "*.json")))
    assert len(files) == 7
    for f in files:
        res = r.invoke(cli, remotes + ["relation-tuple", "create", f])
        assert res.exit_code == 0, res.output

    def check(subject, relation, object):
        return r.invoke(
            cli, remotes + ["check", subject, relation, "videos", object]
        ).exit_code

    # the documented outcomes (reference example README scenario)
    assert check("cat lady", "owner", "/cats") == 0
    assert check("cat lady", "owner", "/cats/1.mp4") == 0  # via /cats#owner
    assert check("cat lady", "view", "/cats/1.mp4") == 0  # two indirections
    assert check("*", "view", "/cats/1.mp4") == 0  # public
    assert check("*", "view", "/cats/2.mp4") == 1  # 2.mp4 is not public
    assert check("dog guy", "view", "/cats/1.mp4") == 1

    # expand shows the owner chain and the public leaf
    res = r.invoke(cli, remotes + ["expand", "view", "videos", "/cats/1.mp4"])
    assert res.exit_code == 0, res.output
    assert "cat lady" in res.output and "*" in res.output


def test_tuple_files_validate_against_schema():
    import jsonschema

    with open(
        os.path.join(
            os.path.dirname(__file__), "..", ".schema",
            "relation_tuple.schema.json",
        )
    ) as f:
        schema = json.load(f)
    for path in glob.glob(
        os.path.join(EXAMPLE_DIR, "relation-tuples", "*.json")
    ):
        with open(path) as f:
            jsonschema.validate(json.load(f), schema)


def test_config_schema_file_matches_code():
    """.schema/config.schema.json is the exported contract for
    driver.config.CONFIG_SCHEMA — they must not drift."""
    from keto_tpu.driver.config import CONFIG_SCHEMA

    with open(
        os.path.join(
            os.path.dirname(__file__), "..", ".schema", "config.schema.json"
        )
    ) as f:
        assert json.load(f) == CONFIG_SCHEMA


def test_openapi_spec_routes_cover_rest_surface():
    """spec/api.json documents every route the REST apps register."""
    with open(
        os.path.join(os.path.dirname(__file__), "..", "spec", "api.json")
    ) as f:
        spec = json.load(f)
    paths = spec["paths"]
    for route, methods in {
        "/check": {"get", "post"},
        "/check/batch": {"post"},
        "/expand": {"get"},
        "/relation-tuples": {"get", "put", "delete", "patch"},
        "/relation-tuples/list-objects": {"get"},
        "/relation-tuples/list-subjects": {"get"},
        "/health/alive": {"get"},
        "/health/ready": {"get"},
        "/version": {"get"},
    }.items():
        assert route in paths, route
        assert methods <= set(paths[route]), route
