"""Snaptoken (zookie) contract + the replicated read plane.

Three layers, cheapest first:

- token algebra: the ``z<version>.<segment>.<offset>`` spelling round-
  trips, bare-int legacy tokens keep parsing, garbage raises;
- write-ack monotonicity across every persistence backend (the store
  matrix fixture) and structured-token minting on the durable store;
- the follower's consistency surface: ``wait_for_version`` honoring the
  freshness window (bounce at zero, wait inside it, typed ErrFollowerLag
  with real lag numbers past it), the LATEST sentinel resolving against
  the leader's position, and a live leader->follower pair over the real
  /replication HTTP routes (checkpoint bootstrap + WAL tail replay).

The SIGKILL-the-leader promotion drill lives in tools/soak.py
(--restart) and the 1-leader/2-follower registry-level cluster in
tools/replication_gate.py; both run as tools/check.sh gates.
"""

import asyncio
import threading
import time

import pytest

from keto_tpu.engine.qos import NamespaceQos, QosThrottled
from keto_tpu.relationtuple.definitions import RelationTuple, SubjectID
from keto_tpu.replication.follower import FollowerReplicator
from keto_tpu.replication.token import (
    LATEST_SENTINEL,
    SnapToken,
    encode_snaptoken,
    parse_snaptoken,
)
from keto_tpu.store import InMemoryTupleStore
from keto_tpu.utils.errors import ErrFollowerLag, ErrReadOnlyFollower


def _tup(i: int) -> RelationTuple:
    return RelationTuple(
        namespace="n", object=f"o{i}", relation="view",
        subject=SubjectID(id="alice"),
    )


# -- token algebra ------------------------------------------------------------


def test_token_roundtrip():
    t = SnapToken(7, 3, 1200)
    assert t.encode() == "z7.3.1200"
    assert parse_snaptoken("z7.3.1200") == t
    assert str(t) == t.encode()
    assert encode_snaptoken(9) == "z9.0.0"


def test_bare_int_tokens_still_parse():
    # the pre-replication spelling (and what WAL-less SQL stores mint)
    assert parse_snaptoken("42") == SnapToken(42, 0, 0)
    assert parse_snaptoken("0") == SnapToken(0, 0, 0)


@pytest.mark.parametrize(
    "bad", ["", "z1.2", "zx.y.z", "not-a-token", "z-1.0.0", "1.2.3"]
)
def test_garbage_tokens_raise(bad):
    with pytest.raises(ValueError):
        parse_snaptoken(bad)


def test_ordering_is_by_version_alone():
    # segment/offset are diagnostic cursor material, never freshness
    newer = parse_snaptoken("z5.1.10")
    older = parse_snaptoken("z4.9.99999")
    assert newer.version > older.version


# -- write-ack monotonicity ---------------------------------------------------


def test_write_ack_tokens_monotonic_across_backends(store, nsmgr):
    nsmgr.add("n")
    versions = []
    for i in range(6):
        store.write_relation_tuples(_tup(i))
        current_token = getattr(store, "current_token", None)
        token = (
            str(current_token())
            if current_token is not None
            else str(store.version)
        )
        versions.append(parse_snaptoken(token).version)
    assert versions == sorted(versions)
    assert len(set(versions)) == len(versions), "acks must be strict"


def test_durable_store_mints_structured_tokens(tmp_path):
    from keto_tpu.store import DurableTupleStore

    s = DurableTupleStore(
        InMemoryTupleStore(), str(tmp_path / "wal"), sync="always"
    )
    try:
        tokens = []
        for i in range(4):
            s.write_relation_tuples(_tup(i))
            tokens.append(parse_snaptoken(str(s.current_token())))
        assert [t.version for t in tokens] == [1, 2, 3, 4]
        # every ack names durable bytes: a real segment, advancing offsets
        assert all(t.segment >= 1 for t in tokens)
        offsets = [t.offset for t in tokens]
        assert offsets == sorted(offsets) and len(set(offsets)) == 4
    finally:
        s.close_durable()


# -- follower waits: the two consistency modes --------------------------------


def _follower(tmp_path, store=None, **kw):
    return FollowerReplicator(
        store if store is not None else InMemoryTupleStore(),
        "http://127.0.0.1:1",  # never dialed in the wait-only tests
        scratch_dir=str(tmp_path / "scratch"),
        **kw,
    )


def test_zero_window_bounces_with_lag_details(tmp_path):
    rep = _follower(tmp_path)
    rep.leader_version = 5
    with pytest.raises(ErrFollowerLag) as ei:
        rep.wait_for_version(5, timeout_s=0.0)
    assert ei.value.lag_versions == 5
    assert ei.value.retry_after_s >= 1
    details = ei.value.envelope()["error"]["details"]
    assert details["lag_versions"] == 5


def test_wait_honors_the_freshness_window(tmp_path):
    rep = _follower(tmp_path)
    rep.leader_version = 3
    t0 = time.monotonic()
    with pytest.raises(ErrFollowerLag):
        rep.wait_for_version(3, timeout_s=0.3)
    elapsed = time.monotonic() - t0
    assert 0.25 <= elapsed < 3.0, elapsed


def test_wait_returns_once_replay_passes_the_token(tmp_path):
    store = InMemoryTupleStore()
    rep = _follower(tmp_path, store)
    rep.leader_version = 1

    def catch_up():
        time.sleep(0.05)
        store.apply_replicated_delta(1, [_tup(1)], [])
        with rep._cv:
            rep._cv.notify_all()

    threading.Thread(target=catch_up, daemon=True).start()
    assert rep.wait_for_version(1, timeout_s=5.0) == 1


def test_latest_sentinel_resolves_to_leader_position(tmp_path):
    store = InMemoryTupleStore()
    rep = _follower(tmp_path, store)
    rep.leader_version = 2
    store.apply_replicated_delta(1, [_tup(1)], [])
    store.apply_replicated_delta(2, [_tup(2)], [])
    assert rep.wait_for_version(LATEST_SENTINEL, timeout_s=0.0) == 2
    # behind the leader, a zero-window latest read bounces
    rep.leader_version = 3
    with pytest.raises(ErrFollowerLag):
        rep.wait_for_version(LATEST_SENTINEL, timeout_s=0.0)


def test_read_only_follower_error_contract():
    e = ErrReadOnlyFollower()
    assert "read-only follower" in str(e)
    assert "leader" in e.envelope()["error"]["message"]


# -- live leader -> follower over the real HTTP routes ------------------------


@pytest.fixture
def leader_http(tmp_path):
    """A durable store serving the three /replication routes on a bare
    aiohttp app — the leader's replication half without the engine
    stack (the registry-level cluster is tools/replication_gate.py)."""
    from aiohttp import web

    from keto_tpu.replication.leader import ReplicationSource
    from keto_tpu.store import DurableTupleStore

    store = DurableTupleStore(
        InMemoryTupleStore(), str(tmp_path / "wal"), sync="always"
    )
    src = ReplicationSource(store, poll_interval_s=0.01)
    app = web.Application()
    src.register(app)
    loop = asyncio.new_event_loop()
    thread = threading.Thread(target=loop.run_forever, daemon=True)
    thread.start()

    async def serve():
        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        return runner, site._server.sockets[0].getsockname()[1]

    runner, port = asyncio.run_coroutine_threadsafe(
        serve(), loop
    ).result(timeout=60)
    yield store, port
    asyncio.run_coroutine_threadsafe(runner.cleanup(), loop).result(
        timeout=10
    )
    loop.call_soon_threadsafe(loop.stop)
    thread.join(timeout=5)


def test_follower_bootstraps_from_checkpoint_and_tails(
    leader_http, tmp_path
):
    store, port = leader_http
    for i in range(5):
        store.write_relation_tuples(_tup(i))

    rep = FollowerReplicator(
        InMemoryTupleStore(),
        f"http://127.0.0.1:{port}",
        scratch_dir=str(tmp_path / "f1"),
        poll_interval_s=0.01,
    )
    seeded = rep.bootstrap()
    # the leader cuts a checkpoint on demand: the follower seeds from
    # it, not from replaying history
    assert seeded["seeded_version"] == 5
    assert rep.store.version == 5

    # live tail: new leader writes arrive through poll_once replay
    for i in range(5, 8):
        store.write_relation_tuples(_tup(i))
    deadline = time.monotonic() + 30
    while rep.store.version < 8 and time.monotonic() < deadline:
        rep.poll_once(wait_ms=200)
    assert rep.store.version == 8
    assert {t.object for t in rep.store.all_tuples()} == {
        f"o{i}" for i in range(8)
    }
    assert rep.applied_total >= 3
    assert rep.lag_versions() == 0

    # the ack token the leader minted is now servable here...
    token = parse_snaptoken(str(store.current_token()))
    assert rep.wait_for_version(token.version, timeout_s=0.0) == 8
    # ...and a token from the future bounces inside the window
    with pytest.raises(ErrFollowerLag):
        rep.wait_for_version(token.version + 1, timeout_s=0.05)


def test_follower_reseeds_when_cursor_is_pruned(leader_http, tmp_path):
    store, port = leader_http
    for i in range(3):
        store.write_relation_tuples(_tup(i))
    rep = FollowerReplicator(
        InMemoryTupleStore(),
        f"http://127.0.0.1:{port}",
        scratch_dir=str(tmp_path / "f2"),
        poll_interval_s=0.01,
    )
    rep.bootstrap()
    # point the cursor at a segment that never existed: the leader
    # answers reset and the follower re-seeds from a fresh checkpoint
    rep._cursor = [999999, 0]
    store.write_relation_tuples(_tup(99))
    rep.poll_once()
    assert rep.reseeds_total == 1
    assert rep._cursor == [0, 0]
    deadline = time.monotonic() + 30
    while rep.store.version < 4 and time.monotonic() < deadline:
        rep.poll_once(wait_ms=200)
    assert rep.store.version == 4


# -- per-tenant QoS -----------------------------------------------------------


def test_qos_throttles_per_namespace_not_globally():
    clock = [0.0]
    qos = NamespaceQos(rate=10.0, burst=5.0, clock=lambda: clock[0])
    for _ in range(5):
        qos.admit("hot")
    with pytest.raises(QosThrottled) as ei:
        qos.admit("hot")
    assert ei.value.namespace == "hot"
    assert ei.value.retry_after_s >= 1
    qos.admit("cold")  # another tenant's bucket is untouched
    clock[0] += 1.0  # refill: 10 tokens/s against a 5-token burst cap
    qos.admit("hot", 5)


def test_qos_overrides_and_unlimited_default():
    qos = NamespaceQos(
        rate=0.0,  # default: admit everything
        burst=100.0,
        overrides={"metered": {"rate": 1.0, "burst": 1.0}},
        clock=lambda: 0.0,
    )
    for _ in range(1000):
        qos.admit("free")
    qos.admit("metered")
    with pytest.raises(QosThrottled):
        qos.admit("metered")
    assert qos.stats()["overrides"]["metered"]["rate"] == 1.0


def test_qos_batch_admission_is_per_namespace_counts():
    qos = NamespaceQos(rate=10.0, burst=10.0, clock=lambda: 0.0)
    qos.admit_counts({"a": 6, "b": 6})  # separate buckets: both fit
    with pytest.raises(QosThrottled):
        qos.admit_counts({"a": 6})  # a's bucket only has 4 left
