"""Manager contract tests — the port of the reference's exported contract
suites (reference internal/relationtuple/manager_requirements.go:19-447 and
manager_isolation.go:44-138). Any tuple-store backend must pass these."""

import pytest

from keto_tpu.namespace import MemoryNamespaceManager
from keto_tpu.relationtuple import (
    RelationQuery,
    RelationTuple,
    SubjectID,
    SubjectSet,
)
from keto_tpu.store import InMemoryTupleStore
from keto_tpu.utils import (
    ErrMalformedPageToken,
    ErrNotFound,
    PaginationOptions,
)


@pytest.fixture
def ns(nsmgr):
    def add(name):
        nsmgr.add(name)
        return name

    return add


class TestWrite:
    def test_write_and_read_back(self, store, ns):
        nspace = ns("write-ns")
        tuples = [
            RelationTuple(nspace, "obj", "rel", SubjectID("sub")),
            RelationTuple(nspace, "obj", "rel", SubjectSet(nspace, "sub obj", "sub rel")),
        ]
        store.write_relation_tuples(*tuples)
        for t in tuples:
            resp, next_page = store.get_relation_tuples(t.to_query())
            assert next_page == ""
            assert resp == [t]

    def test_unknown_namespace(self, store):
        with pytest.raises(ErrNotFound):
            store.write_relation_tuples(
                RelationTuple("unknown namespace", "", "", SubjectID(""))
            )

    def test_duplicate_write_is_idempotent(self, store, ns):
        """Writing the same tuple twice must leave exactly one row, and the
        re-insert must report not-fresh (an empty inserted delta). The
        SubjectSet case is the one MySQL historically got wrong: unique
        indexes over raw nullable subject columns never collide because
        NULL != NULL there — the dedup index coalesces them instead."""
        nspace = ns("dup-ns")
        deltas = []
        store.subscribe_deltas(
            lambda v, ins, dels: deltas.append(len(ins or []))
        )
        for t in [
            RelationTuple(nspace, "obj", "rel", SubjectID("sub")),
            RelationTuple(
                nspace, "obj", "rel", SubjectSet(nspace, "grp", "member")
            ),
        ]:
            store.write_relation_tuples(t)
            store.write_relation_tuples(t)
            resp, _ = store.get_relation_tuples(t.to_query())
            assert resp == [t]
        assert deltas == [1, 0, 1, 0]


class TestGet:
    def test_query_combinations(self, store, ns):
        nspace = ns("get-ns")
        tuples = [
            RelationTuple(nspace, f"o {i % 2}", f"r {i % 4}", SubjectID(f"s {i}"))
            for i in range(10)
        ]
        store.write_relation_tuples(*tuples)

        cases = [
            (RelationQuery(namespace=nspace), tuples),
            (RelationQuery(namespace=nspace, object="o 0"), tuples[0::2]),
            (RelationQuery(namespace=nspace, relation="r 0"), tuples[0::4]),
            (
                RelationQuery(namespace=nspace, object="o 0", relation="r 0"),
                [tuples[0], tuples[4], tuples[8]],
            ),
            (
                RelationQuery(namespace=nspace, subject=SubjectID("s 3")),
                [tuples[3]],
            ),
            (
                RelationQuery(
                    namespace=nspace, object="o 1", relation="r 1", subject=SubjectID("s 1")
                ),
                [tuples[1]],
            ),
        ]
        for query, expected in cases:
            resp, next_page = store.get_relation_tuples(query)
            assert next_page == ""
            assert resp == expected

    def test_unknown_namespace_query(self, store):
        with pytest.raises(ErrNotFound):
            store.get_relation_tuples(RelationQuery(namespace="nope"))

    def test_pagination(self, store, ns):
        nspace = ns("page-ns")
        tuples = [
            RelationTuple(nspace, "o", "r", SubjectID(f"s{i:03d}")) for i in range(25)
        ]
        store.write_relation_tuples(*tuples)

        seen, token, pages = [], "", 0
        while True:
            resp, token = store.get_relation_tuples(
                RelationQuery(namespace=nspace),
                PaginationOptions(token=token, size=10),
            )
            seen += resp
            pages += 1
            if not token:
                break
        assert pages == 3
        assert seen == tuples

    def test_malformed_page_token(self, store, ns):
        nspace = ns("tok-ns")
        with pytest.raises(ErrMalformedPageToken):
            store.get_relation_tuples(
                RelationQuery(namespace=nspace),
                PaginationOptions(token="not a token !!"),
            )


class TestDelete:
    def test_delete(self, store, ns):
        nspace = ns("del-ns")
        keep = RelationTuple(nspace, "o", "r", SubjectID("keep"))
        kill = RelationTuple(nspace, "o", "r", SubjectID("kill"))
        store.write_relation_tuples(keep, kill)
        store.delete_relation_tuples(kill)
        resp, _ = store.get_relation_tuples(RelationQuery(namespace=nspace))
        assert resp == [keep]

    def test_delete_all_by_query(self, store, ns):
        nspace = ns("delall-ns")
        a = [RelationTuple(nspace, "a", "r", SubjectID(f"s{i}")) for i in range(3)]
        b = [RelationTuple(nspace, "b", "r", SubjectID(f"s{i}")) for i in range(3)]
        store.write_relation_tuples(*a, *b)
        store.delete_all_relation_tuples(RelationQuery(namespace=nspace, object="a"))
        resp, _ = store.get_relation_tuples(RelationQuery(namespace=nspace))
        assert resp == b


class TestTransact:
    def test_insert_and_delete_atomically(self, store, ns):
        nspace = ns("tx-ns")
        old = RelationTuple(nspace, "o", "r", SubjectID("old"))
        new = RelationTuple(nspace, "o", "r", SubjectID("new"))
        store.write_relation_tuples(old)
        store.transact_relation_tuples(insert=[new], delete=[old])
        resp, _ = store.get_relation_tuples(RelationQuery(namespace=nspace))
        assert resp == [new]

    def test_rollback_on_invalid_insert(self, store, ns):
        # reference manager_requirements.go:399-445: a failing insert must
        # leave previously-existing state untouched and apply nothing
        nspace = ns("rb-ns")
        existing = RelationTuple(nspace, "o", "r", SubjectID("existing"))
        store.write_relation_tuples(existing)
        good = RelationTuple(nspace, "o", "r", SubjectID("good"))
        bad = RelationTuple("unknown-ns", "o", "r", SubjectID("bad"))
        with pytest.raises(ErrNotFound):
            store.transact_relation_tuples(insert=[good, bad], delete=[existing])
        resp, _ = store.get_relation_tuples(RelationQuery(namespace=nspace))
        assert resp == [existing]


class TestIsolation:
    def test_network_isolation(self):
        # two stores with different network ids over the same namespace
        # config must not see each other's tuples
        # (reference manager_isolation.go:44-138)
        nsmgr = MemoryNamespaceManager()
        nsmgr.add("iso")
        s1 = InMemoryTupleStore(namespace_manager=nsmgr, network_id="net-1")
        s2 = InMemoryTupleStore(namespace_manager=nsmgr, network_id="net-2")
        t = RelationTuple("iso", "o", "r", SubjectID("s"))
        s1.write_relation_tuples(t)
        assert s1.get_relation_tuples(RelationQuery(namespace="iso"))[0] == [t]
        assert s2.get_relation_tuples(RelationQuery(namespace="iso"))[0] == []


class TestVersionCounter:
    def test_version_bumps_on_mutation(self, store, ns):
        nspace = ns("ver-ns")
        v0 = store.version
        store.write_relation_tuples(RelationTuple(nspace, "o", "r", SubjectID("s")))
        assert store.version == v0 + 1
        store.delete_all_relation_tuples(RelationQuery(namespace=nspace))
        assert store.version == v0 + 2

    def test_subscribe(self, store, ns):
        nspace = ns("sub-ns")
        got = []
        store.subscribe(got.append)
        store.write_relation_tuples(RelationTuple(nspace, "o", "r", SubjectID("s")))
        assert got == [store.version]


class TestOrderedNotify:
    """Deltas must be delivered in strict version order even when writes
    race (ADVICE r4 medium: out-of-order deltas collapsed the replica pool
    and broke the write overlay). Covers every OrderedNotifier backend."""

    @pytest.mark.parametrize("kind", ["memory", "columnar", "sqlite"])
    def test_concurrent_writers_deliver_in_version_order(self, kind, tmp_path):
        import threading

        if kind == "memory":
            store = InMemoryTupleStore()
        elif kind == "columnar":
            from keto_tpu.store import ColumnarTupleStore

            store = ColumnarTupleStore()
        else:
            from keto_tpu.persistence.sqlite import SQLiteTupleStore

            store = SQLiteTupleStore(str(tmp_path / "ord.db"))

        versions: list[int] = []
        deltas: list[int] = []
        store.subscribe(versions.append)
        store.subscribe_deltas(lambda v, ins, dels: deltas.append(v))

        n_threads, n_writes = 8, 25
        barrier = threading.Barrier(n_threads)

        def writer(wid):
            barrier.wait()
            for i in range(n_writes):
                store.write_relation_tuples(
                    RelationTuple("ns", f"o{wid}", "r", SubjectID(f"s{i}"))
                )

        threads = [
            threading.Thread(target=writer, args=(w,))
            for w in range(n_threads)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

        total = n_threads * n_writes
        assert versions == list(range(1, total + 1))
        assert deltas == list(range(1, total + 1))
