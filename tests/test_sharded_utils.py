"""Host-side unit tests for the sharded engine's helpers (no device mesh
needed; the full sharded behavior runs under the virtual-mesh subprocess
suite in test_multichip_sharded.py)."""

from keto_tpu.parallel.sharded import ShardedCheckEngine


class TestShardedBucket:
    def test_bucket_batch_terminates_for_non_power_of_two_data_axis(self):
        class Dummy:
            pass

        for n_data in (1, 2, 3, 5, 6, 7, 8):
            eng = Dummy()
            eng.n_data = n_data
            for n in (1, 7, 8, 9, 100, 4096):
                b = ShardedCheckEngine._bucket_batch(eng, n)
                assert b >= n
                assert b % n_data == 0
                per = b // n_data
                assert per & (per - 1) == 0  # per-device slice is a pow2
