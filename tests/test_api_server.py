"""End-to-end API tests: boot both planes on free ports, drive them over
real REST (httpx) and real gRPC (grpcio) — the e2e pattern of the reference
(internal/e2e/full_suit_test.go: same scenarios through multiple client
transports against a live server)."""

import asyncio
import json
import threading

import grpc
import httpx
import pytest

from keto_tpu.api import (
    acl_pb2,
    check_service_pb2,
    expand_service_pb2,
    health_pb2,
    read_service_pb2,
    version_pb2,
    write_service_pb2,
)
from keto_tpu.api.services import (
    CheckServiceStub,
    ExpandServiceStub,
    HealthStub,
    ReadServiceStub,
    VersionServiceStub,
    WriteServiceStub,
)
from keto_tpu.driver import Config, Registry


class ServerFixture:
    """Runs a Registry's planes in a background asyncio loop thread.
    Accepts a Config or a pre-built Registry (factory-made)."""

    def __init__(self, config: Config | Registry):
        self.registry = (
            config if isinstance(config, Registry) else Registry(config)
        )
        self.loop = asyncio.new_event_loop()
        self.thread = threading.Thread(target=self.loop.run_forever, daemon=True)
        self.thread.start()
        fut = asyncio.run_coroutine_threadsafe(
            self.registry.start_all(), self.loop
        )
        self.read_port, self.write_port = fut.result(timeout=180)

    def stop(self):
        asyncio.run_coroutine_threadsafe(
            self.registry.stop_all(), self.loop
        ).result(timeout=10)
        self.loop.call_soon_threadsafe(self.loop.stop)
        self.thread.join(timeout=5)


@pytest.fixture(scope="module")
def server():
    cfg = Config(
        values={
            "namespaces": [{"id": 1, "name": "videos"}, {"id": 2, "name": "n"}],
            "log": {"level": "error"},
            "serve": {
                "read": {"port": 0, "host": "127.0.0.1"},
                "write": {"port": 0, "host": "127.0.0.1"},
            },
        }
    )
    s = ServerFixture(cfg)
    yield s
    s.stop()


@pytest.fixture()
def clean(server):
    server.registry.store().delete_all_relation_tuples(
        __import__("keto_tpu.relationtuple", fromlist=["RelationQuery"]).RelationQuery()
    )
    return server


def rest(server, plane="read"):
    port = server.read_port if plane == "read" else server.write_port
    # generous timeout: shape growth can trigger an XLA recompile mid-test
    return httpx.Client(base_url=f"http://127.0.0.1:{port}", timeout=60)


class TestRest:
    def test_health_and_version(self, clean):
        with rest(clean) as c:
            assert c.get("/health/alive").json() == {"status": "ok"}
            assert c.get("/health/ready").status_code == 200
            assert "version" in c.get("/version").json()
        with rest(clean, "write") as c:
            assert c.get("/health/alive").status_code == 200

    def test_list_and_expand_snaptoken_validation(self, clean):
        """REST twins of the gRPC snaptoken fields: accepted when valid,
        400 when malformed (keto_tpu extension; the reference has none)."""
        with rest(clean) as c:
            r = c.get(
                "/relation-tuples",
                params={"namespace": "n", "snaptoken": "1"},
            )
            assert r.status_code == 200
            r = c.get(
                "/relation-tuples",
                params={"namespace": "n", "snaptoken": "bogus"},
            )
            assert r.status_code == 400
            r = c.get(
                "/expand",
                params={
                    "namespace": "n",
                    "object": "o",
                    "relation": "r",
                    "snaptoken": "bogus",
                },
            )
            assert r.status_code == 400

    def test_create_check_expand_flow(self, clean):
        with rest(clean, "write") as w:
            r = w.put(
                "/relation-tuples",
                json={
                    "namespace": "videos",
                    "object": "/cats",
                    "relation": "owner",
                    "subject_id": "cat lady",
                },
            )
            assert r.status_code == 201, r.text
            assert r.headers["Location"].startswith("/relation-tuples?")
            r = w.put(
                "/relation-tuples",
                json={
                    "namespace": "videos",
                    "object": "/cats/1.mp4",
                    "relation": "view",
                    "subject_set": {
                        "namespace": "videos",
                        "object": "/cats",
                        "relation": "owner",
                    },
                },
            )
            assert r.status_code == 201
        with rest(clean) as c:
            r = c.get(
                "/check",
                params={
                    "namespace": "videos",
                    "object": "/cats/1.mp4",
                    "relation": "view",
                    "subject_id": "cat lady",
                },
            )
            assert r.status_code == 200
            assert r.json() == {"allowed": True}
            r = c.get(
                "/check",
                params={
                    "namespace": "videos",
                    "object": "/cats/1.mp4",
                    "relation": "view",
                    "subject_id": "dog guy",
                },
            )
            assert r.status_code == 403
            assert r.json() == {"allowed": False}
            # POST form
            r = c.post(
                "/check",
                json={
                    "namespace": "videos",
                    "object": "/cats",
                    "relation": "owner",
                    "subject_id": "cat lady",
                },
            )
            assert r.status_code == 200
            # expand
            r = c.get(
                "/expand",
                params={
                    "namespace": "videos",
                    "object": "/cats/1.mp4",
                    "relation": "view",
                },
            )
            assert r.status_code == 200
            tree = r.json()
            assert tree["type"] == "union"
            assert tree["children"][0]["subject_set"]["relation"] == "owner"

    def test_list_and_pagination(self, clean):
        with rest(clean, "write") as w:
            for i in range(5):
                assert (
                    w.put(
                        "/relation-tuples",
                        json={
                            "namespace": "n",
                            "object": "o",
                            "relation": "r",
                            "subject_id": f"u{i}",
                        },
                    ).status_code
                    == 201
                )
        with rest(clean) as c:
            r = c.get(
                "/relation-tuples",
                params={"namespace": "n", "page_size": 2},
            )
            body = r.json()
            assert len(body["relation_tuples"]) == 2
            assert body["next_page_token"]
            r2 = c.get(
                "/relation-tuples",
                params={
                    "namespace": "n",
                    "page_size": 2,
                    "page_token": body["next_page_token"],
                },
            )
            assert len(r2.json()["relation_tuples"]) == 2
            # bad token -> 400
            r3 = c.get(
                "/relation-tuples",
                params={"namespace": "n", "page_token": "$$garbage$$"},
            )
            assert r3.status_code == 400
            assert "error" in r3.json()

    def test_patch_and_delete(self, clean):
        with rest(clean, "write") as w:
            r = w.patch(
                "/relation-tuples",
                json=[
                    {
                        "action": "insert",
                        "relation_tuple": {
                            "namespace": "n",
                            "object": "o",
                            "relation": "r",
                            "subject_id": "alice",
                        },
                    },
                    {
                        "action": "insert",
                        "relation_tuple": {
                            "namespace": "n",
                            "object": "o",
                            "relation": "r",
                            "subject_id": "bob",
                        },
                    },
                ],
            )
            assert r.status_code == 204
            # unknown action -> 400, nothing applied
            r = w.patch(
                "/relation-tuples",
                json=[
                    {
                        "action": "upsert",
                        "relation_tuple": {
                            "namespace": "n",
                            "object": "o",
                            "relation": "r",
                            "subject_id": "eve",
                        },
                    }
                ],
            )
            assert r.status_code == 400
            r = w.delete(
                "/relation-tuples", params={"namespace": "n", "subject_id": "bob"}
            )
            assert r.status_code == 204
        with rest(clean) as c:
            body = c.get("/relation-tuples", params={"namespace": "n"}).json()
            subjects = {t["subject_id"] for t in body["relation_tuples"]}
            assert subjects == {"alice"}

    def test_unknown_namespace_404(self, clean):
        with rest(clean, "write") as w:
            r = w.put(
                "/relation-tuples",
                json={
                    "namespace": "nope",
                    "object": "o",
                    "relation": "r",
                    "subject_id": "alice",
                },
            )
            assert r.status_code == 404
            assert r.json()["error"]["code"] == 404

    def test_malformed_subject_params(self, clean):
        with rest(clean) as c:
            r = c.get(
                "/check",
                params={
                    "namespace": "n",
                    "object": "o",
                    "relation": "r",
                    "subject_id": "x",
                    "subject_set.namespace": "n",
                    "subject_set.object": "o",
                    "subject_set.relation": "r",
                },
            )
            assert r.status_code == 400


def grpc_channel(server, plane="read"):
    port = server.read_port if plane == "read" else server.write_port
    return grpc.insecure_channel(f"127.0.0.1:{port}")


class TestGrpc:
    def test_write_then_check_expand_list(self, clean):
        with grpc_channel(clean, "write") as wch:
            write = WriteServiceStub(wch)
            deltas = [
                write_service_pb2.RelationTupleDelta(
                    action=write_service_pb2.RelationTupleDelta.INSERT,
                    relation_tuple=acl_pb2.RelationTuple(
                        namespace="n",
                        object="o",
                        relation="r",
                        subject=acl_pb2.Subject(id="alice"),
                    ),
                ),
                write_service_pb2.RelationTupleDelta(
                    action=write_service_pb2.RelationTupleDelta.INSERT,
                    relation_tuple=acl_pb2.RelationTuple(
                        namespace="n",
                        object="o2",
                        relation="r",
                        subject=acl_pb2.Subject(
                            set=acl_pb2.SubjectSet(
                                namespace="n", object="o", relation="r"
                            )
                        ),
                    ),
                ),
            ]
            resp = write.TransactRelationTuples(
                write_service_pb2.TransactRelationTuplesRequest(
                    relation_tuple_deltas=deltas
                )
            )
            assert len(resp.snaptokens) == 2
            assert resp.snaptokens[0] != ""

        with grpc_channel(clean) as rch:
            check = CheckServiceStub(rch)
            r = check.Check(
                check_service_pb2.CheckRequest(
                    namespace="n",
                    object="o2",
                    relation="r",
                    subject=acl_pb2.Subject(id="alice"),
                )
            )
            assert r.allowed is True
            assert r.snaptoken != ""
            r = check.Check(
                check_service_pb2.CheckRequest(
                    namespace="n",
                    object="o2",
                    relation="r",
                    subject=acl_pb2.Subject(id="mallory"),
                )
            )
            assert r.allowed is False

            expand = ExpandServiceStub(rch)
            t = expand.Expand(
                expand_service_pb2.ExpandRequest(
                    subject=acl_pb2.Subject(
                        set=acl_pb2.SubjectSet(
                            namespace="n", object="o2", relation="r"
                        )
                    )
                )
            )
            assert t.tree.node_type == expand_service_pb2.NODE_TYPE_UNION

            read = ReadServiceStub(rch)
            lst = read.ListRelationTuples(
                read_service_pb2.ListRelationTuplesRequest(
                    query=read_service_pb2.ListRelationTuplesRequest.Query(
                        namespace="n"
                    )
                )
            )
            assert len(lst.relation_tuples) == 2

    def test_list_snaptoken_and_expand_mask(self, clean):
        """ListRelationTuples honors snaptoken (validated; live-store reads
        are always at least as fresh) and implements expand_mask projection
        — both fields the reference ignores (read_service.proto:22-23)."""
        with grpc_channel(clean, "write") as wch:
            WriteServiceStub(wch).TransactRelationTuples(
                write_service_pb2.TransactRelationTuplesRequest(
                    relation_tuple_deltas=[
                        write_service_pb2.RelationTupleDelta(
                            action=write_service_pb2.RelationTupleDelta.INSERT,
                            relation_tuple=acl_pb2.RelationTuple(
                                namespace="n", object="o", relation="r",
                                subject=acl_pb2.Subject(id="alice"),
                            ),
                        )
                    ]
                )
            )
        with grpc_channel(clean) as rch:
            read = ReadServiceStub(rch)
            q = read_service_pb2.ListRelationTuplesRequest.Query(
                namespace="n"
            )
            # snaptoken from a write is honored (trivially fresh here)
            lst = read.ListRelationTuples(
                read_service_pb2.ListRelationTuplesRequest(
                    query=q, snaptoken="1"
                )
            )
            assert len(lst.relation_tuples) == 1
            # malformed snaptoken -> INVALID_ARGUMENT
            with pytest.raises(grpc.RpcError) as e:
                read.ListRelationTuples(
                    read_service_pb2.ListRelationTuplesRequest(
                        query=q, snaptoken="not-a-version"
                    )
                )
            assert e.value.code() == grpc.StatusCode.INVALID_ARGUMENT
            # expand_mask projects the returned tuples
            req = read_service_pb2.ListRelationTuplesRequest(query=q)
            req.expand_mask.paths.extend(["namespace", "object"])
            lst = read.ListRelationTuples(req)
            t0 = lst.relation_tuples[0]
            assert t0.namespace == "n" and t0.object == "o"
            assert t0.relation == "" and not t0.HasField("subject")
            # unknown mask path -> INVALID_ARGUMENT
            bad = read_service_pb2.ListRelationTuplesRequest(query=q)
            bad.expand_mask.paths.append("commit_time")
            with pytest.raises(grpc.RpcError) as e:
                read.ListRelationTuples(bad)
            assert e.value.code() == grpc.StatusCode.INVALID_ARGUMENT

    def test_expand_snaptoken(self, clean):
        """ExpandRequest.snaptoken: honored (expand reads the live
        snapshot) and validated."""
        with grpc_channel(clean, "write") as wch:
            WriteServiceStub(wch).TransactRelationTuples(
                write_service_pb2.TransactRelationTuplesRequest(
                    relation_tuple_deltas=[
                        write_service_pb2.RelationTupleDelta(
                            action=write_service_pb2.RelationTupleDelta.INSERT,
                            relation_tuple=acl_pb2.RelationTuple(
                                namespace="n", object="doc", relation="view",
                                subject=acl_pb2.Subject(id="bob"),
                            ),
                        )
                    ]
                )
            )
        subject = acl_pb2.Subject(
            set=acl_pb2.SubjectSet(namespace="n", object="doc", relation="view")
        )
        with grpc_channel(clean) as rch:
            expand = ExpandServiceStub(rch)
            t = expand.Expand(
                expand_service_pb2.ExpandRequest(
                    subject=subject, snaptoken="1"
                )
            )
            assert t.tree.node_type == expand_service_pb2.NODE_TYPE_UNION
            with pytest.raises(grpc.RpcError) as e:
                expand.Expand(
                    expand_service_pb2.ExpandRequest(
                        subject=subject, snaptoken="xyz"
                    )
                )
            assert e.value.code() == grpc.StatusCode.INVALID_ARGUMENT

    def test_check_without_subject_invalid(self, clean):
        with grpc_channel(clean) as rch:
            check = CheckServiceStub(rch)
            with pytest.raises(grpc.RpcError) as e:
                check.Check(
                    check_service_pb2.CheckRequest(
                        namespace="n", object="o", relation="r"
                    )
                )
            assert e.value.code() == grpc.StatusCode.INVALID_ARGUMENT

    def test_version_and_health(self, clean):
        with grpc_channel(clean) as rch:
            v = VersionServiceStub(rch).GetVersion(
                version_pb2.GetVersionRequest()
            )
            assert v.version
            h = HealthStub(rch).Check(health_pb2.HealthCheckRequest())
            assert h.status == health_pb2.HealthCheckResponse.SERVING
        with grpc_channel(clean, "write") as wch:
            h = HealthStub(wch).Check(health_pb2.HealthCheckRequest())
            assert h.status == health_pb2.HealthCheckResponse.SERVING

    def test_delete_by_query(self, clean):
        with grpc_channel(clean, "write") as wch:
            write = WriteServiceStub(wch)
            write.TransactRelationTuples(
                write_service_pb2.TransactRelationTuplesRequest(
                    relation_tuple_deltas=[
                        write_service_pb2.RelationTupleDelta(
                            action=write_service_pb2.RelationTupleDelta.INSERT,
                            relation_tuple=acl_pb2.RelationTuple(
                                namespace="n",
                                object="o",
                                relation="r",
                                subject=acl_pb2.Subject(id=f"u{i}"),
                            ),
                        )
                        for i in range(3)
                    ]
                )
            )
            write.DeleteRelationTuples(
                write_service_pb2.DeleteRelationTuplesRequest(
                    query=write_service_pb2.DeleteRelationTuplesRequest.Query(
                        namespace="n", object="o"
                    )
                )
            )
        with grpc_channel(clean) as rch:
            lst = ReadServiceStub(rch).ListRelationTuples(
                read_service_pb2.ListRelationTuplesRequest(
                    query=read_service_pb2.ListRelationTuplesRequest.Query(
                        namespace="n"
                    )
                )
            )
            assert len(lst.relation_tuples) == 0
