"""Dialect split: engine-specific SQL generation and migration overlays
(reference per-dialect persistence, internal/persistence/sql/persister.go:50-51,
internal/x/dbx/dsn_testutils.go:22-74)."""

import os

import pytest

from keto_tpu.persistence import (
    DIALECTS,
    PostgresDialect,
    SQLiteDialect,
    dialect_for_dsn,
)
from keto_tpu.persistence.dialect import CockroachDialect, MySQLDialect
from keto_tpu.persistence.migrator import load_migrations
from keto_tpu.persistence.sqlstore import _MIGRATIONS_DIR


class TestDialects:
    def test_placeholder_rewrite(self):
        pg = PostgresDialect()
        assert pg.sql("SELECT * FROM t WHERE a = ? AND b = ?") == (
            "SELECT * FROM t WHERE a = %s AND b = %s"
        )
        sq = SQLiteDialect()
        assert sq.sql("a = ?") == "a = ?"

    def test_insert_ignore_spellings(self):
        cols = ("a", "b")
        assert "INSERT OR IGNORE" in SQLiteDialect().insert_ignore("t", cols)
        pg = PostgresDialect().insert_ignore("t", cols)
        assert "ON CONFLICT DO NOTHING" in pg and "INSERT INTO t" in pg

    def test_mysql_spellings(self):
        my = MySQLDialect()
        assert "INSERT IGNORE INTO t" in my.insert_ignore("t", ("a", "b"))
        assert my.sql("a = ?") == "a = %s"

    def test_dsn_dispatch(self):
        d, native = dialect_for_dsn("memory")
        assert d.name == "sqlite" and native == ":memory:"
        d, native = dialect_for_dsn("sqlite:///tmp/x.db")
        assert d.name == "sqlite" and native == "/tmp/x.db"
        d, native = dialect_for_dsn("postgres://u:p@h/db")
        assert d.name == "postgres" and native == "postgres://u:p@h/db"
        d, native = dialect_for_dsn("cockroach://u@h:26257/db")
        assert d.name == "cockroach" and native == "postgres://u@h:26257/db"
        d, native = dialect_for_dsn("mysql://u:p@h/db")
        assert d.name == "mysql"
        with pytest.raises(ValueError):
            dialect_for_dsn("mongodb://nope")

    def test_four_dialects_registered(self):
        # the reference persister's engine matrix
        # (internal/persistence/sql/persister.go:50-51)
        assert set(DIALECTS) == {"sqlite", "postgres", "cockroach", "mysql"}

    def test_postgres_connect_falls_back_to_wire_driver(self, pgfake_server):
        """Without psycopg, the dialect connects through the in-tree v3
        wire driver — the postgres path works in the bare image."""
        conn = PostgresDialect().connect(
            f"postgres://keto@127.0.0.1:{pgfake_server.port}/wiretest"
        )
        try:
            cur = conn.cursor()
            cur.execute("SELECT %s + %s", (20, 22))
            assert cur.fetchone()[0] == 42
            conn.rollback()
        finally:
            conn.close()

    def test_wire_driver_types_and_rowcount(self, pgfake_server):
        from keto_tpu.persistence import pgwire

        conn = pgwire.connect(
            f"postgres://keto@127.0.0.1:{pgfake_server.port}/wiretypes"
        )
        try:
            cur = conn.cursor()
            cur.execute(
                "CREATE TABLE t (n BIGINT, x DOUBLE PRECISION, s TEXT)"
            )
            cur.execute(
                "INSERT INTO t VALUES (%s, %s, %s), (%s, %s, %s)",
                (1, 1.5, "it's", 2, None, None),
            )
            assert cur.rowcount == 2
            conn.commit()
            cur.execute("SELECT n, x, s FROM t ORDER BY n")
            rows = cur.fetchall()
            assert rows == [(1, 1.5, "it's"), (2, None, None)]
            conn.rollback()
        finally:
            conn.close()

    def test_wire_driver_error_surfaces_and_recovers(self, pgfake_server):
        from keto_tpu.persistence import pgwire

        conn = pgwire.connect(
            f"postgres://keto@127.0.0.1:{pgfake_server.port}/wireerr"
        )
        try:
            with pytest.raises(pgwire.Error):
                conn.cursor().execute("SELECT * FROM missing_table")
            conn.rollback()
            cur = conn.cursor()
            cur.execute("SELECT %s", ("ok",))
            assert cur.fetchone() == ("ok",)
            conn.rollback()
        finally:
            conn.close()


class TestMigrationOverlays:
    def test_postgres_overlay_replaces_generic(self):
        generic = {
            m.version: m for m in load_migrations(_MIGRATIONS_DIR)
        }
        pg = {
            m.version: m
            for m in load_migrations(
                _MIGRATIONS_DIR, dialect=DIALECTS["postgres"]
            )
        }
        assert set(pg) == set(generic)  # same version ladder
        v0 = "20220101000000"
        assert "AUTOINCREMENT" in generic[v0].up_sql
        assert "BIGSERIAL" in pg[v0].up_sql
        # no postgres down overlay: the generic down carries over
        assert pg[v0].down_sql == generic[v0].down_sql
        # portable migrations identical on both
        v1 = "20220101000001"
        assert pg[v1].up_sql == generic[v1].up_sql

    def test_sqlite_dialect_sees_generic_files_only(self):
        sq = {
            m.version: m
            for m in load_migrations(
                _MIGRATIONS_DIR, dialect=DIALECTS["sqlite"]
            )
        }
        assert "AUTOINCREMENT" in sq["20220101000000"].up_sql

    def test_overlay_file_naming_is_complete(self):
        """Every per-dialect overlay has a generic twin (else a dialect
        would silently gain a migration others lack)."""
        for fname in os.listdir(_MIGRATIONS_DIR):
            for marker in (".postgres.", ".mysql.", ".cockroach."):
                if marker in fname:
                    twin = fname.replace(marker, ".")
                    assert os.path.exists(
                        os.path.join(_MIGRATIONS_DIR, twin)
                    ), f"{fname} has no generic twin {twin}"

    def test_mysql_and_cockroach_overlays_load(self):
        v0 = "20220101000000"
        my = {
            m.version: m
            for m in load_migrations(
                _MIGRATIONS_DIR, dialect=DIALECTS["mysql"]
            )
        }
        assert "AUTO_INCREMENT" in my[v0].up_sql
        cr = {
            m.version: m
            for m in load_migrations(
                _MIGRATIONS_DIR, dialect=DIALECTS["cockroach"]
            )
        }
        assert "BIGSERIAL" in cr[v0].up_sql
        # same version ladder everywhere
        generic = {m.version for m in load_migrations(_MIGRATIONS_DIR)}
        assert set(my) == set(cr) == generic
