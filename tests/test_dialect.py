"""Dialect split: engine-specific SQL generation and migration overlays
(reference per-dialect persistence, internal/persistence/sql/persister.go:50-51,
internal/x/dbx/dsn_testutils.go:22-74)."""

import os

import pytest

from keto_tpu.persistence import (
    DIALECTS,
    PostgresDialect,
    SQLiteDialect,
    dialect_for_dsn,
)
from keto_tpu.persistence.migrator import load_migrations
from keto_tpu.persistence.sqlstore import _MIGRATIONS_DIR


class TestDialects:
    def test_placeholder_rewrite(self):
        pg = PostgresDialect()
        assert pg.sql("SELECT * FROM t WHERE a = ? AND b = ?") == (
            "SELECT * FROM t WHERE a = %s AND b = %s"
        )
        sq = SQLiteDialect()
        assert sq.sql("a = ?") == "a = ?"

    def test_insert_ignore_spellings(self):
        cols = ("a", "b")
        assert "INSERT OR IGNORE" in SQLiteDialect().insert_ignore("t", cols)
        pg = PostgresDialect().insert_ignore("t", cols)
        assert "ON CONFLICT DO NOTHING" in pg and "INSERT INTO t" in pg

    def test_dsn_dispatch(self):
        d, native = dialect_for_dsn("memory")
        assert d.name == "sqlite" and native == ":memory:"
        d, native = dialect_for_dsn("sqlite:///tmp/x.db")
        assert d.name == "sqlite" and native == "/tmp/x.db"
        d, native = dialect_for_dsn("postgres://u:p@h/db")
        assert d.name == "postgres" and native == "postgres://u:p@h/db"
        with pytest.raises(ValueError):
            dialect_for_dsn("mongodb://nope")

    def test_postgres_connect_without_driver_raises_clearly(self):
        has_driver = True
        try:
            import psycopg  # noqa: F401
        except ImportError:
            try:
                import psycopg2  # noqa: F401
            except ImportError:
                has_driver = False
        if has_driver:
            pytest.skip("a postgres driver exists in this image")
        with pytest.raises(RuntimeError, match="no postgres driver"):
            PostgresDialect().connect("postgres://localhost/x")


class TestMigrationOverlays:
    def test_postgres_overlay_replaces_generic(self):
        generic = {
            m.version: m for m in load_migrations(_MIGRATIONS_DIR)
        }
        pg = {
            m.version: m
            for m in load_migrations(
                _MIGRATIONS_DIR, dialect=DIALECTS["postgres"]
            )
        }
        assert set(pg) == set(generic)  # same version ladder
        v0 = "20220101000000"
        assert "AUTOINCREMENT" in generic[v0].up_sql
        assert "BIGSERIAL" in pg[v0].up_sql
        # no postgres down overlay: the generic down carries over
        assert pg[v0].down_sql == generic[v0].down_sql
        # portable migrations identical on both
        v1 = "20220101000001"
        assert pg[v1].up_sql == generic[v1].up_sql

    def test_sqlite_dialect_sees_generic_files_only(self):
        sq = {
            m.version: m
            for m in load_migrations(
                _MIGRATIONS_DIR, dialect=DIALECTS["sqlite"]
            )
        }
        assert "AUTOINCREMENT" in sq["20220101000000"].up_sql

    def test_overlay_file_naming_is_complete(self):
        """Every *.postgres.*.sql has a generic twin (else a dialect would
        silently gain a migration others lack)."""
        for fname in os.listdir(_MIGRATIONS_DIR):
            if ".postgres." in fname:
                twin = fname.replace(".postgres.", ".")
                assert os.path.exists(
                    os.path.join(_MIGRATIONS_DIR, twin)
                ), f"{fname} has no generic twin {twin}"
