"""Units for the cluster observability plane: the shared OpenMetrics
parser, leader-side membership, health rollup thresholds, the federation
scraper (driven synchronously with canned expositions and a fake clock),
the follower heartbeater, and the bench trajectory/rotation helpers.

The live 1-leader/2-follower drill — heartbeats over HTTP, federated
/metrics linting in both formats, the stitched hedged trace — runs in
tools/replication_gate.py, not here.
"""

from __future__ import annotations

import json

import pytest

from keto_tpu.cluster import ClusterHeartbeater, ClusterMembership
from keto_tpu.telemetry import (
    FederationScraper,
    MetricsRegistry,
    parse_text,
    rollup_health,
)

# -- the shared OpenMetrics parser (linter + federation scraper) ----------

EXPOSITION = """\
# HELP keto_replication_lag_versions versions behind
# TYPE keto_replication_lag_versions gauge
keto_replication_lag_versions 3
# HELP keto_http_requests_total requests
# TYPE keto_http_requests_total counter
keto_http_requests_total{code="200"} 90
keto_http_requests_total{code="503"} 10
# HELP keto_slo_events_total events
# TYPE keto_slo_events_total counter
keto_slo_events_total 1000
# HELP keto_slo_bad_events_total bad events
# TYPE keto_slo_bad_events_total counter
keto_slo_bad_events_total 20
# HELP keto_slo_burn_rate burn
# TYPE keto_slo_burn_rate gauge
keto_slo_burn_rate{window="fast"} 0.5
keto_slo_burn_rate{window="slow"} 0.25
"""


class TestParseText:
    def test_families_and_values(self):
        parsed = parse_text(EXPOSITION)
        assert not parsed.errors
        assert parsed.value("keto_replication_lag_versions") == 3.0
        assert (
            parsed.value("keto_slo_burn_rate", {"window": "fast"}) == 0.5
        )
        assert parsed.value("keto_slo_burn_rate", {"window": "none"}) is None
        assert parsed.value("keto_absent") is None

    def test_sum_counter_sums_children(self):
        parsed = parse_text(EXPOSITION)
        assert parsed.sum_counter("keto_http_requests_total") == 100.0
        assert parsed.sum_counter("keto_slo_events_total") == 1000.0
        assert parsed.sum_counter("keto_absent_total") is None

    def test_samples_named(self):
        parsed = parse_text(EXPOSITION)
        rows = parsed.samples_named("keto_http_requests_total")
        assert {s.labels["code"] for s in rows} == {"200", "503"}

    def test_errors_carry_line_numbers(self):
        parsed = parse_text("what even is this line\n")
        assert parsed.errors
        assert any(e.startswith("line 1:") for e in parsed.errors)

    def test_openmetrics_requires_eof(self):
        body = "# HELP x y\n# TYPE x gauge\nx 1\n"
        assert any(
            "EOF" in e for e in parse_text(body, openmetrics=True).errors
        )
        assert not parse_text(body + "# EOF\n", openmetrics=True).errors


# -- membership -----------------------------------------------------------


class TestMembership:
    def test_upsert_requires_instance_id(self):
        m = ClusterMembership()
        with pytest.raises(ValueError):
            m.upsert({"role": "follower"})

    def test_heartbeats_accumulate_and_first_seen_sticks(self):
        t = [100.0]
        m = ClusterMembership(member_timeout_s=5.0, clock=lambda: t[0])
        m.upsert({"instance_id": "f0"})
        t[0] = 101.0
        row = m.upsert({"instance_id": "f0", "version": 7})
        assert row["heartbeats"] == 2
        assert row["first_seen"] == 100.0
        assert m.get("f0")["version"] == 7

    def test_liveness_ages_out_but_row_survives(self):
        t = [100.0]
        m = ClusterMembership(member_timeout_s=5.0, clock=lambda: t[0])
        m.upsert({"instance_id": "f0"})
        assert m.members()[0]["alive"]
        t[0] = 106.0
        rows = m.members()
        assert len(rows) == 1 and not rows[0]["alive"]
        assert rows[0]["age_s"] == 6.0
        assert m.alive() == []

    def test_members_sorted_by_join_order(self):
        t = [1.0]
        m = ClusterMembership(clock=lambda: t[0])
        for inst in ("c", "a", "b"):
            m.upsert({"instance_id": inst})
            t[0] += 1.0
        assert [r["instance_id"] for r in m.members()] == ["c", "a", "b"]


# -- health rollup --------------------------------------------------------


class TestRollupHealth:
    def test_green_when_unknown_fields_are_none(self):
        level, reasons = rollup_health(
            {"alive": True, "lag_versions": None, "burn_rate": None}
        )
        assert level == "green" and reasons == []

    def test_down_is_red(self):
        level, reasons = rollup_health({"alive": False, "age_s": 42})
        assert level == "red"
        assert any("down" in r for r in reasons)

    def test_threshold_ladder(self):
        assert rollup_health({"lag_versions": 99})[0] == "green"
        assert rollup_health({"lag_versions": 100})[0] == "yellow"
        assert rollup_health({"lag_versions": 10000})[0] == "red"
        assert rollup_health({"burn_rate": 1.5})[0] == "yellow"
        assert rollup_health({"burn_rate": 2.0})[0] == "red"
        assert rollup_health({"staleness_seconds": 60.0})[0] == "red"

    def test_breaker_and_recovery(self):
        assert rollup_health({"breaker": 1.0})[0] == "red"
        assert rollup_health({"breaker": 0.5})[0] == "yellow"
        assert rollup_health({"recovering": True})[0] == "yellow"
        assert rollup_health({"breaker": 0.0})[0] == "green"

    def test_custom_thresholds(self):
        view = {"lag_versions": 50}
        assert rollup_health(view)[0] == "green"
        assert (
            rollup_health(view, {"lag_versions_yellow": 10})[0] == "yellow"
        )
        # None-valued overrides fall back to the defaults
        assert (
            rollup_health(view, {"lag_versions_yellow": None})[0] == "green"
        )


# -- federation scraper ---------------------------------------------------


def _scraper(expositions: dict, clock, **kw):
    """A scraper over a canned {url: exposition_text} fleet."""
    # NB: "or" would discard an injected-but-empty membership (it has
    # __len__, so an empty table is falsy)
    membership = kw.pop("membership", None)
    if membership is None:
        membership = ClusterMembership(member_timeout_s=60.0)

    def fetch(url: str, timeout_s: float) -> str:
        if url not in expositions:
            raise OSError(f"no route to {url}")
        return expositions[url]

    metrics = MetricsRegistry()
    scraper = FederationScraper(
        membership,
        metrics,
        objective=kw.pop("objective", 0.99),
        fetch_fn=fetch,
        clock=clock,
        **kw,
    )
    return scraper, membership, metrics


class TestFederationScraper:
    def test_pre_cycle_status_is_unknown(self):
        scraper, membership, _ = _scraper({}, clock=lambda: 0.0)
        membership.upsert({"instance_id": "f0"})
        st = scraper.status()
        assert st["cluster"]["health"] == "unknown"
        assert st["cluster"]["members"] == 1

    def test_run_once_federates_and_reexports(self):
        t = [100.0]
        scraper, membership, metrics = _scraper(
            {"http://f0/metrics": EXPOSITION}, clock=lambda: t[0]
        )
        membership.upsert(
            {
                "instance_id": "f0",
                "role": "follower",
                "read_url": "http://f0",
            }
        )
        st = scraper.run_once()
        (view,) = st["members"]
        assert view["scrape_ok"] and view["lag_versions"] == 3.0
        assert view["burn_rate"] == 0.5  # max(fast, slow)
        assert view["health"] == "green"
        # re-exported instance-labeled series parse with our own parser
        parsed = parse_text(metrics.expose())
        assert (
            parsed.value(
                "keto_cluster_replication_lag_versions", {"instance": "f0"}
            )
            == 3.0
        )
        assert (
            parsed.value("keto_cluster_member_up", {"instance": "f0"}) == 1.0
        )
        assert scraper.status() is st  # cached, no inline scrape

    def test_qps_and_aggregate_burn_from_counter_deltas(self):
        t = [100.0]
        expositions = {"http://f0/metrics": EXPOSITION}
        scraper, membership, metrics = _scraper(
            expositions, clock=lambda: t[0], objective=0.99
        )
        membership.upsert(
            {
                "instance_id": "f0",
                "role": "follower",
                "read_url": "http://f0",
            }
        )
        st = scraper.run_once()  # first cycle only records prev counters
        assert st["members"][0]["qps"] is None
        assert st["cluster"]["aggregate_burn_rate"] == 0.0

        # +200 requests, +200 events (+10 bad) over 10s
        expositions["http://f0/metrics"] = (
            EXPOSITION.replace('code="200"} 90', 'code="200"} 280')
            .replace('code="503"} 10', 'code="503"} 20')
            .replace("keto_slo_events_total 1000", "keto_slo_events_total 1200")
            .replace(
                "keto_slo_bad_events_total 20", "keto_slo_bad_events_total 30"
            )
        )
        t[0] = 110.0
        st = scraper.run_once()
        assert st["members"][0]["qps"] == 20.0
        # (10 bad / 200 events) / (1 - 0.99) budget = 5x burn
        assert st["cluster"]["aggregate_burn_rate"] == 5.0
        assert (
            parse_text(metrics.expose()).value(
                "keto_cluster_slo_burn_rate_aggregate"
            )
            == 5.0
        )

    def test_leader_lag_defaults_to_zero(self):
        scraper, membership, _ = _scraper(
            {"http://l/metrics": "# TYPE x gauge\nx 1\n"},
            clock=lambda: 0.0,
        )
        membership.upsert(
            {"instance_id": "l0", "role": "leader", "read_url": "http://l"}
        )
        (view,) = scraper.run_once()["members"]
        assert view["lag_versions"] == 0.0
        assert view["staleness_seconds"] == 0.0
        assert view["health"] == "green"

    def test_scrape_failure_is_counted_not_fatal(self):
        scraper, membership, metrics = _scraper({}, clock=lambda: 0.0)
        membership.upsert(
            {
                "instance_id": "f0",
                "role": "follower",
                "read_url": "http://gone",
            }
        )
        st = scraper.run_once()
        (view,) = st["members"]
        assert not view["scrape_ok"] and "OSError" in view["scrape_error"]
        assert st["cluster"]["scrape"]["errors"] == 1
        parsed = parse_text(metrics.expose())
        assert (
            parsed.value(
                "keto_cluster_scrape_errors_total", {"instance": "f0"}
            )
            == 1.0
        )

    def test_self_payload_makes_standalone_a_member(self):
        scraper, _, _ = _scraper(
            {},
            clock=lambda: 0.0,
            self_payload_fn=lambda: {"instance_id": "me", "role": "leader"},
        )
        st = scraper.run_once()
        assert [m["instance_id"] for m in st["members"]] == ["me"]
        assert st["cluster"]["alive"] == 1

    def test_member_read_urls_skips_dead_and_selfless(self):
        t = [100.0]
        membership = ClusterMembership(
            member_timeout_s=5.0, clock=lambda: t[0]
        )
        scraper, _, _ = _scraper(
            {}, clock=lambda: t[0], membership=membership
        )
        membership.upsert({"instance_id": "f0", "read_url": "http://f0"})
        membership.upsert({"instance_id": "f1"})  # no read_url
        t[0] = 102.0
        membership.upsert({"instance_id": "f2", "read_url": "http://f2"})
        t[0] = 107.0  # f0/f1 aged out, f2 still fresh
        assert scraper.member_read_urls() == [("f2", "http://f2")]

    def test_status_json_round_trips(self):
        scraper, membership, _ = _scraper({}, clock=lambda: 0.0)
        membership.upsert({"instance_id": "f0"})
        json.dumps(scraper.run_once())  # must not raise


# -- heartbeater ----------------------------------------------------------


class TestHeartbeater:
    def test_beat_once_posts_payload_to_cluster_route(self):
        posted = []
        hb = ClusterHeartbeater(
            "http://leader:4467/",
            lambda: {"instance_id": "f0", "version": 9},
            post_fn=lambda url, payload: posted.append((url, payload)),
        )
        assert hb.beat_once()
        assert posted == [
            (
                "http://leader:4467/cluster/heartbeat",
                {"instance_id": "f0", "version": 9},
            )
        ]
        assert hb.beats == 1 and hb.errors == 0

    def test_failures_are_swallowed_and_counted(self):
        def post(url, payload):
            raise ConnectionError("leader is restarting")

        hb = ClusterHeartbeater(
            "http://leader:4467", lambda: {"instance_id": "f0"}, post_fn=post
        )
        assert not hb.beat_once()
        assert hb.beats == 0 and hb.errors == 1
        assert "leader is restarting" in hb.last_error
        st = hb.status()
        assert st["errors"] == 1 and not st["running"]


# -- bench satellites: trajectory + heartbeat rotation --------------------


class TestBenchTrajectory:
    def test_no_prior_run_no_deltas(self, monkeypatch):
        import bench

        monkeypatch.setattr(bench, "_load_prev_headline", lambda: None)
        assert bench._trajectory({"value": 100}) == (None, [])

    def test_deltas_and_regressions_when_comparable(self, monkeypatch):
        import bench

        prev = {
            "metric": "check_rps",
            "value": 1000.0,
            "batch_p95_ms": 10.0,
            "config": "rbac1m",
            "backend": "cpu",
        }
        monkeypatch.setattr(
            bench, "_load_prev_headline", lambda: ("BENCH_r09.json", prev)
        )
        now = {
            "value": 700.0,  # -30% throughput: regression
            "batch_p95_ms": 11.0,  # +10% latency: within noise
            "config": "rbac1m",
            "backend": "cpu",
        }
        vs_prev, regressions = bench._trajectory(now)
        assert vs_prev["config_match"] is True
        assert vs_prev["deltas"]["value"]["delta_pct"] == -30.0
        assert regressions == ["value"]

    def test_incomparable_runs_report_deltas_but_never_flag(
        self, monkeypatch
    ):
        import bench

        prev = {
            "metric": "check_rps",
            "value": 1000.0,
            "config": "rbac100m",
            "backend": "cpu",
        }
        monkeypatch.setattr(
            bench, "_load_prev_headline", lambda: ("BENCH_r09.json", prev)
        )
        vs_prev, regressions = bench._trajectory(
            {"value": 10.0, "config": "smoke", "backend": "cpu"}
        )
        assert vs_prev["config_match"] is False
        assert "value" in vs_prev["deltas"]
        assert regressions == []


class TestBenchHeartbeatRotation:
    def test_rotates_at_cap_and_keeps_one_generation(
        self, tmp_path, monkeypatch
    ):
        import bench

        monkeypatch.setenv("BENCH_HEARTBEAT_MAX_BYTES", "64")
        path = tmp_path / "hb.jsonl"
        path.write_bytes(b"x" * 100)
        bench._rotate_heartbeat(str(path))
        assert not path.exists()
        assert (tmp_path / "hb.jsonl.1").read_bytes() == b"x" * 100

    def test_under_cap_untouched(self, tmp_path, monkeypatch):
        import bench

        monkeypatch.setenv("BENCH_HEARTBEAT_MAX_BYTES", "1024")
        path = tmp_path / "hb.jsonl"
        path.write_bytes(b"x" * 10)
        bench._rotate_heartbeat(str(path))
        assert path.exists() and not (tmp_path / "hb.jsonl.1").exists()

    def test_missing_file_is_fine(self, tmp_path):
        import bench

        bench._rotate_heartbeat(str(tmp_path / "absent.jsonl"))
