"""NodeVocab.lookup_bulk: the vectorized hash-index encode path must agree
exactly with the dict, across growth, rebuilds, unknowns, and forced
64-bit hash collisions."""

import numpy as np
import pytest

from keto_tpu.graph.vocab import NodeVocab


def _keys(n, prefix="k"):
    return [(f"{prefix}{i}", f"o{i}", "r") for i in range(n)]


class TestLookupBulk:
    def test_matches_dict_with_unknowns(self):
        v = NodeVocab()
        keys = _keys(500) + [(f"u{i}",) for i in range(100)]
        v.intern_bulk(keys)
        probe = keys[::3] + _keys(50, prefix="missing") + [("nope",)]
        got = v.lookup_bulk(probe)
        expect = [
            v.lookup(k) if v.lookup(k) is not None else -1 for k in probe
        ]
        assert got.tolist() == expect

    def test_incremental_growth_and_rebuild(self):
        v = NodeVocab()
        v.intern_bulk(_keys(10))
        assert v.lookup_bulk([("k3", "o3", "r")]).tolist() == [
            v.lookup(("k3", "o3", "r"))
        ]
        # grow far past the first table size: forces a from-scratch rebuild
        v.intern_bulk(_keys(5000, prefix="x"))
        probe = [("x4999", "o4999", "r"), ("k3", "o3", "r"), ("gone",)]
        assert v.lookup_bulk(probe).tolist() == [
            v.lookup(probe[0]),
            v.lookup(probe[1]),
            -1,
        ]

    def test_forced_hash_collisions_detected_on_insert(self):
        """Different keys, identical 64-bit hash: every colliding hash must
        land in the collision set so lookups route through the exact dict
        (only the first key of a colliding group lives in the table)."""
        v = NodeVocab()
        keys = _keys(64)
        v.intern_bulk(keys)
        # build a degraded index where EVERY key hashes to 42
        n = len(v._key_of)
        need = 1 << int(n / 0.6).bit_length()
        mask = need - 1
        slots = np.zeros(need, dtype=np.int64)
        slot_ids = np.full(need, -1, dtype=np.int32)
        collisions: set = set()
        all_h = np.full(n, 42, dtype=np.int64)
        NodeVocab._insert_hashes(
            mask, slots, slot_ids, collisions, all_h,
            np.arange(n, dtype=np.int32),
        )
        assert collisions == {42}
        # exactly one entry made it into the table (the rest must use the
        # dict): the winning slot holds a valid id
        stored = slot_ids[slot_ids >= 0]
        assert len(stored) == 1 and 0 <= stored[0] < n

    def test_empty(self):
        v = NodeVocab()
        assert v.lookup_bulk([]).tolist() == []
        assert v.lookup_bulk([("a",)]).tolist() == [-1]

    @pytest.mark.parametrize("seed", range(3))
    def test_random_interleaved_intern_lookup(self, seed):
        rng = np.random.default_rng(seed)
        v = NodeVocab()
        universe = _keys(2000) + [(f"s{i}",) for i in range(800)]
        for _ in range(6):
            batch = [
                universe[i]
                for i in rng.integers(len(universe), size=300)
            ]
            v.intern_bulk(batch)
            probe = [
                universe[i]
                for i in rng.integers(len(universe), size=200)
            ]
            got = v.lookup_bulk(probe)
            expect = [
                v.lookup(k) if v.lookup(k) is not None else -1
                for k in probe
            ]
            assert got.tolist() == expect
