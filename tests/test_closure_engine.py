"""ClosureCheckEngine vs the host oracle: the gather-only closure path must
agree bit-for-bit with host BFS on every graph — the same scenario matrix the
lockstep device engines run (reference internal/check/engine_test.go:45-581),
plus closure-specific edges: overflow fallback, interior-limit fallback, and
write-driven closure rebuilds."""

import numpy as np
import pytest

from keto_tpu.engine import CheckEngine
from keto_tpu.engine.closure import ClosureCheckEngine
from keto_tpu.graph import SnapshotManager
from keto_tpu.graph.interior import build_interior
from keto_tpu.relationtuple import RelationTuple, SubjectSet
from keto_tpu.store import InMemoryTupleStore

from test_device_engines import random_store


def t(s: str) -> RelationTuple:
    return RelationTuple.from_string(s)


@pytest.fixture
def store():
    return InMemoryTupleStore()


def make_engines(store, max_depth=5, **kw):
    mgr = SnapshotManager(store)
    return (
        CheckEngine(store, max_depth=max_depth),
        ClosureCheckEngine(mgr, max_depth=max_depth, **kw),
    )


class TestClosureScenarios:
    def test_direct_inclusion(self, store):
        store.write_relation_tuples(t("n:obj#access@alice"))
        _, eng = make_engines(store)
        assert eng.subject_is_allowed(t("n:obj#access@alice"))
        assert not eng.subject_is_allowed(t("n:obj#access@bob"))

    def test_indirect_inclusion_two_levels(self, store):
        store.write_relation_tuples(
            t("n:obj#access@(n:org#member)"),
            t("n:org#member@(n:team#member)"),
            t("n:team#member@alice"),
        )
        _, eng = make_engines(store)
        assert eng.subject_is_allowed(t("n:obj#access@alice"))
        assert eng.subject_is_allowed(t("n:obj#access@(n:team#member)"))
        assert not eng.subject_is_allowed(t("n:obj#access@mallory"))

    def test_wrong_object_or_relation(self, store):
        store.write_relation_tuples(t("n:obj#access@alice"))
        _, eng = make_engines(store)
        assert not eng.subject_is_allowed(t("n:other#access@alice"))
        assert not eng.subject_is_allowed(t("n:obj#write@alice"))
        assert not eng.subject_is_allowed(t("other:obj#access@alice"))

    def test_circular_tuples_terminate(self, store):
        store.write_relation_tuples(t("n:a#r@(n:b#r)"), t("n:b#r@(n:a#r)"))
        _, eng = make_engines(store)
        assert not eng.subject_is_allowed(t("n:a#r@alice"))
        # the sets themselves are mutually reachable (cycle of length 2)
        assert eng.subject_is_allowed(t("n:a#r@(n:a#r)"))
        assert eng.subject_is_allowed(t("n:a#r@(n:b#r)"))

    def test_depth_budget(self, store):
        store.write_relation_tuples(
            t("n:obj#r@(n:s1#m)"),
            t("n:s1#m@(n:s2#m)"),
            t("n:s2#m@(n:s3#m)"),
            t("n:s3#m@alice"),
        )
        _, eng = make_engines(store, max_depth=10)
        req = t("n:obj#r@alice")
        assert not eng.subject_is_allowed(req, max_depth=3)
        assert eng.subject_is_allowed(req, max_depth=4)
        assert eng.subject_is_allowed(req, max_depth=0)  # clamps to global
        assert eng.subject_is_allowed(req, max_depth=99)

    def test_global_max_depth_precedence(self, store):
        store.write_relation_tuples(
            t("n:obj#r@(n:s1#m)"),
            t("n:s1#m@(n:s2#m)"),
            t("n:s2#m@alice"),
        )
        _, eng = make_engines(store, max_depth=2)
        assert not eng.subject_is_allowed(t("n:obj#r@alice"), max_depth=50)

    def test_subject_set_exact_match_semantics(self, store):
        store.write_relation_tuples(t("n:obj#r@alice"))
        _, eng = make_engines(store)
        assert not eng.subject_is_allowed(t("n:obj#r@(n:obj#r)"))

    def test_set_target_depth_one(self, store):
        # direct set-to-set edge must be allowed at depth 1 exactly
        store.write_relation_tuples(t("n:obj#r@(n:grp#m)"), t("n:grp#m@u"))
        _, eng = make_engines(store)
        assert eng.subject_is_allowed(t("n:obj#r@(n:grp#m)"), max_depth=1)

    def test_unknown_everything(self, store):
        _, eng = make_engines(store)
        assert not eng.subject_is_allowed(t("no:thing#here@nobody"))

    def test_write_visibility_rebuilds_closure(self, store):
        _, eng = make_engines(store)
        req = t("n:obj#r@alice")
        assert not eng.subject_is_allowed(req)
        store.write_relation_tuples(req)
        assert eng.subject_is_allowed(req)
        store.delete_relation_tuples(req)
        assert not eng.subject_is_allowed(req)
        # indirect path appears after incremental writes
        store.write_relation_tuples(t("n:obj#r@(n:g#m)"))
        store.write_relation_tuples(t("n:g#m@alice"))
        assert eng.subject_is_allowed(req)

    def test_batch_mixed_depths(self, store):
        store.write_relation_tuples(
            t("n:obj#r@(n:s1#m)"),
            t("n:s1#m@alice"),
            t("n:obj#r@bob"),
        )
        _, eng = make_engines(store)
        reqs = [t("n:obj#r@alice"), t("n:obj#r@bob"), t("n:obj#r@eve")]
        assert eng.batch_check(reqs, depths=[1, 1, 5]) == [False, True, False]
        assert eng.batch_check(reqs, depths=[2, 1, 5]) == [True, True, False]


def _random_requests(rng, n_objects, n_users, k=64):
    reqs = []
    for _ in range(k):
        obj = f"o{rng.integers(n_objects)}"
        rel = f"r{rng.integers(3)}"
        if rng.random() < 0.3:
            sub = f"n:o{rng.integers(n_objects)}#r{rng.integers(3)}"
        else:
            sub = f"u{rng.integers(n_users)}"
        reqs.append(t(f"n:{obj}#{rel}@({sub})"))
    return reqs


class TestClosureMatchesOracle:
    @pytest.mark.parametrize("seed", range(6))
    def test_random_graphs(self, seed):
        rng = np.random.default_rng(seed)
        store = random_store(rng, n_objects=15, n_users=10, n_edges=120)
        for depth in (1, 2, 3, 5, 8):
            host, eng = make_engines(store, max_depth=depth)
            reqs = _random_requests(rng, 15, 10)
            expect = [host.subject_is_allowed(r) for r in reqs]
            got = eng.batch_check(reqs)
            assert got == expect, f"seed={seed} depth={depth}"

    @pytest.mark.parametrize("seed", range(3))
    def test_random_graphs_per_request_depths(self, seed):
        rng = np.random.default_rng(seed + 50)
        store = random_store(rng, n_objects=12, n_users=8, n_edges=90)
        host, eng = make_engines(store, max_depth=8)
        reqs = _random_requests(rng, 12, 8)
        depths = [int(rng.integers(1, 9)) for _ in reqs]
        expect = [
            host.subject_is_allowed(r, max_depth=d)
            for r, d in zip(reqs, depths)
        ]
        assert eng.batch_check(reqs, depths=depths) == expect

    @pytest.mark.parametrize("seed", range(2))
    def test_overflow_rows_fall_back_exactly(self, seed):
        """Tiny F0/L widths force the overflow path; answers stay exact."""
        rng = np.random.default_rng(seed + 200)
        store = random_store(rng, n_objects=10, n_users=6, n_edges=100)
        host, eng = make_engines(store, max_depth=5, f0_max=1, l_max=1)
        reqs = _random_requests(rng, 10, 6)
        expect = [host.subject_is_allowed(r) for r in reqs]
        assert eng.batch_check(reqs) == expect

    def test_interior_limit_falls_back_whole_batch(self):
        rng = np.random.default_rng(7)
        store = random_store(rng, n_objects=10, n_users=6, n_edges=80)
        host, eng = make_engines(store, max_depth=5, interior_limit=2)
        reqs = _random_requests(rng, 10, 6)
        expect = [host.subject_is_allowed(r) for r in reqs]
        assert eng.batch_check(reqs) == expect


class TestCheckIds:
    @pytest.mark.parametrize("interior_limit", [16384, 2])
    def test_array_api_matches_object_api(self, interior_limit):
        rng = np.random.default_rng(11)
        store = random_store(rng, n_objects=12, n_users=8, n_edges=100)
        host, eng = make_engines(
            store, max_depth=5, interior_limit=interior_limit
        )
        reqs = _random_requests(rng, 12, 8)
        snap = eng.snapshots.snapshot()
        start = np.array(
            [
                snap.node_for_set(r.namespace, r.object, r.relation)
                for r in reqs
            ],
            dtype=np.int64,
        )
        target = np.array(
            [snap.node_for_subject(r.subject) for r in reqs], dtype=np.int64
        )
        from keto_tpu.relationtuple import SubjectID
        is_id = np.array(
            [isinstance(r.subject, SubjectID) for r in reqs]
        )
        expect = [host.subject_is_allowed(r) for r in reqs]
        got = eng.check_ids(start, target, is_id)
        assert got.tolist() == expect

    def test_empty_batch(self):
        rng = np.random.default_rng(13)
        store = random_store(rng, n_objects=6, n_users=4, n_edges=30)
        _, eng = make_engines(store, max_depth=5)
        assert eng.batch_check([]) == []
        got = eng.check_ids(
            np.empty(0, np.int64), np.empty(0, np.int64), np.empty(0, bool)
        )
        assert got.tolist() == []

    def test_huge_max_depth_stays_exact(self):
        """max_depth beyond the uint8 distance range must not produce
        spurious allows (the INF sentinel would collide at depth >= 256)."""
        store = InMemoryTupleStore()
        store.write_relation_tuples(
            RelationTuple.from_string("n:a#r@(n:b#r)"),
            RelationTuple.from_string("n:x#r@(n:y#r)"),
        )
        host, eng = make_engines(store, max_depth=256)
        req = RelationTuple.from_string("n:a#r@(n:y#r)")
        assert host.subject_is_allowed(req) is False
        assert eng.subject_is_allowed(req) is False
        # a depth-255 engine still uses the closure and stays exact
        host2, eng2 = make_engines(store, max_depth=255)
        assert eng2.subject_is_allowed(req) is False
        assert eng2.subject_is_allowed(
            RelationTuple.from_string("n:a#r@(n:b#r)")
        )

    def test_unknown_ids_denied(self):
        rng = np.random.default_rng(12)
        store = random_store(rng, n_objects=6, n_users=4, n_edges=30)
        _, eng = make_engines(store, max_depth=5, interior_limit=2)
        snap = eng.snapshots.snapshot()
        dummy = snap.dummy_node
        got = eng.check_ids(
            np.array([dummy]), np.array([dummy]), np.array([True])
        )
        assert got.tolist() == [False]


class TestInteriorGraph:
    def test_decomposition_shape(self, store):
        store.write_relation_tuples(
            t("n:doc#view@(n:doc#own)"),   # doc#own interior
            t("n:doc#own@(n:team#m)"),     # team#m interior
            t("n:team#m@alice"),           # id sink
            t("n:lonely#r@bob"),           # lonely#r has no in-edges
        )
        snap = SnapshotManager(store).snapshot()
        ig = build_interior(snap)
        assert ig.m == 2  # doc#own, team#m
        interior_nodes = {
            snap.vocab.key(int(i)) for i in ig.interior_ids
        }
        assert interior_nodes == {("n", "doc", "own"), ("n", "team", "m")}
        # direct edge test
        s = snap.node_for_set("n", "team", "m")
        a = snap.vocab.lookup(("alice",))
        assert ig.direct_edge(
            np.array([s], dtype=np.int64), np.array([a], dtype=np.int64)
        ).tolist() == [True]

    def test_wildcard_subject_is_plain_id(self, store):
        # the cat-videos '*' convention: a literal id, nothing special
        store.write_relation_tuples(t("v:/cats/1#view@*"))
        _, eng = make_engines(store)
        assert eng.subject_is_allowed(t("v:/cats/1#view@*"))
        assert not eng.subject_is_allowed(t("v:/cats/2#view@*"))


class TestDeviceView:
    """device_view() serves the same resident closure with
    query_mode=device — answers must match the host path bit-for-bit
    (the bench's device leg rests on this parity)."""

    def test_parity_on_random_graphs(self):
        rng = np.random.default_rng(42)
        for seed in range(3):
            store = random_store(np.random.default_rng(seed), 20, 10, 200)
            oracle, eng = make_engines(store, query_mode="host")
            dview = eng.device_view()
            reqs = store.all_tuples()[:64]
            # mix hits with misses
            reqs += [t(f"miss:obj{i}#rel@nobody{i}") for i in range(16)]
            rng.shuffle(reqs)
            want = eng.batch_check(reqs)
            got = dview.batch_check(reqs)
            assert got == want, f"seed {seed}"
            assert want == oracle.batch_check(reqs), f"seed {seed} vs oracle"

    def test_device_view_requires_resident_closure(self):
        store = InMemoryTupleStore()
        # a real interior node (subject-set indirection) with
        # interior_limit=0 forces the _TooBig fallback state
        store.write_relation_tuples(
            t("n:o#r@(n:g#m)"), t("n:g#m@alice")
        )
        _, eng = make_engines(store, interior_limit=0)
        eng.subject_is_allowed(t("n:o#r@alice"))  # forces _TooBig state
        with pytest.raises(RuntimeError):
            eng.device_view()
