"""Config hot-reload + TLS serving (reference provider.go:58-104: watch the
config file, rebuild the namespace manager on change, immutable DSN/serve
keys; TLS per the serve.*.tls schema)."""

import ssl
import subprocess
import time

import grpc
import httpx
import pytest

from keto_tpu.driver import Config
from keto_tpu.utils.errors import ErrMalformedInput
from tests.test_api_server import ServerFixture


def _write_cfg(path, namespaces, dsn="memory", extra=""):
    ns_lines = "".join(
        f"  - name: {n}\n    id: {i}\n" for i, n in enumerate(namespaces, 1)
    )
    path.write_text(
        f"dsn: {dsn}\nnamespaces:\n{ns_lines}{extra}"
    )


class TestConfigReload:
    def test_reload_applies_mutable_keys(self, tmp_path):
        cfg_file = tmp_path / "keto.yml"
        _write_cfg(cfg_file, ["videos"])
        cfg = Config(config_file=str(cfg_file), env={})
        mgr = cfg.namespace_manager()
        mgr.get_namespace_by_name("videos")

        _write_cfg(cfg_file, ["videos", "files"], extra="log:\n  level: debug\n")
        applied = cfg.reload()
        assert set(applied) == {"namespaces", "log"}
        # the SAME manager object serves the new set (stores hold it)
        mgr.get_namespace_by_name("files")
        assert cfg.get("log.level") == "debug"

    def test_immutable_keys_keep_boot_values(self, tmp_path):
        cfg_file = tmp_path / "keto.yml"
        _write_cfg(cfg_file, ["videos"])
        cfg = Config(config_file=str(cfg_file), env={})
        assert cfg.dsn() == "memory"
        _write_cfg(
            cfg_file, ["videos"], dsn=f"sqlite://{tmp_path}/other.db",
            extra="serve:\n  read:\n    port: 9999\n",
        )
        applied = cfg.reload()
        assert "dsn" not in applied and "serve" not in applied
        assert cfg.dsn() == "memory"
        assert cfg.read_api_port() == 4466

    def test_invalid_reload_keeps_previous_config(self, tmp_path):
        cfg_file = tmp_path / "keto.yml"
        _write_cfg(cfg_file, ["videos"])
        cfg = Config(config_file=str(cfg_file), env={})
        cfg_file.write_text("dsn: memory\nnamespaces: 42\n")
        with pytest.raises(Exception):
            cfg.reload()
        cfg.namespace_manager().get_namespace_by_name("videos")

    def test_inline_to_uri_flip_swaps_inner_manager(self, tmp_path):
        cfg_file = tmp_path / "keto.yml"
        _write_cfg(cfg_file, ["videos"])
        cfg = Config(config_file=str(cfg_file), env={})
        wrapper = cfg.namespace_manager()
        ns_file = tmp_path / "ns.yml"
        ns_file.write_text("- name: remote\n  id: 9\n")
        cfg_file.write_text(f"dsn: memory\nnamespaces: {ns_file}\n")
        assert cfg.reload() == ["namespaces"]
        wrapper.get_namespace_by_name("remote")
        wrapper.close()


class TestServerHotReload:
    def test_namespace_change_visible_while_serving(self, tmp_path):
        cfg_file = tmp_path / "keto.yml"
        _write_cfg(cfg_file, ["videos"])
        cfg = Config(
            config_file=str(cfg_file),
            values={
                "log": {"level": "error"},
                "serve": {
                    "read": {"port": 0, "host": "127.0.0.1"},
                    "write": {"port": 0, "host": "127.0.0.1"},
                }
            },
            env={},
        )
        s = ServerFixture(cfg)
        s.registry._start_config_watcher(poll_interval_s=0.05)
        try:
            # unknown namespace is a 404 before the reload
            r = httpx.put(
                f"http://127.0.0.1:{s.write_port}/relation-tuples",
                json={
                    "namespace": "files",
                    "object": "f1",
                    "relation": "view",
                    "subject_id": "alice",
                },
            )
            assert r.status_code == 404
            _write_cfg(cfg_file, ["videos", "files"])
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                r = httpx.put(
                    f"http://127.0.0.1:{s.write_port}/relation-tuples",
                    json={
                        "namespace": "files",
                        "object": "f1",
                        "relation": "view",
                        "subject_id": "alice",
                    },
                )
                if r.status_code == 201:
                    break
                time.sleep(0.05)
            assert r.status_code == 201
        finally:
            s.stop()


def _make_cert(tmp_path):
    cert = tmp_path / "tls.crt"
    key = tmp_path / "tls.key"
    proc = subprocess.run(
        [
            "openssl", "req", "-x509", "-newkey", "rsa:2048",
            "-keyout", str(key), "-out", str(cert), "-days", "1",
            "-nodes", "-subj", "/CN=127.0.0.1",
            "-addext", "subjectAltName=IP:127.0.0.1",
        ],
        capture_output=True,
    )
    if proc.returncode != 0:
        pytest.skip(f"openssl unavailable: {proc.stderr[:200]}")
    return cert, key


class TestTls:
    def test_both_protocols_through_tls_mux(self, tmp_path):
        cert, key = _make_cert(tmp_path)
        cfg = Config(
            values={
                "namespaces": [{"id": 1, "name": "videos"}],
                "log": {"level": "error"},
                "serve": {
                    "read": {
                        "port": 0,
                        "host": "127.0.0.1",
                        "tls": {
                            "cert": {"path": str(cert)},
                            "key": {"path": str(key)},
                        },
                    },
                    "write": {"port": 0, "host": "127.0.0.1"},
                },
            },
            env={},
        )
        s = ServerFixture(cfg)
        try:
            # HTTPS REST through the TLS mux
            r = httpx.get(
                f"https://127.0.0.1:{s.read_port}/health/alive",
                verify=ssl.create_default_context(cafile=str(cert)),
            )
            assert r.status_code == 200
            # plaintext against the TLS port fails
            with pytest.raises(Exception):
                httpx.get(
                    f"http://127.0.0.1:{s.read_port}/health/alive",
                    timeout=2,
                )
            # gRPC with TLS channel credentials through the same port
            creds = grpc.ssl_channel_credentials(
                root_certificates=cert.read_bytes()
            )
            from keto_tpu.api import health_pb2
            from keto_tpu.api.services import HealthStub

            with grpc.secure_channel(
                f"127.0.0.1:{s.read_port}", creds
            ) as ch:
                resp = HealthStub(ch).Check(
                    health_pb2.HealthCheckRequest(), timeout=10
                )
            assert resp.status == health_pb2.HealthCheckResponse.SERVING
        finally:
            s.stop()


class TestWsNamespaceWatcher:
    """ws:// namespace source (reference watcherx ws URIs,
    internal/driver/config/namespace_watcher.go:48-89): a local websocket
    server pushes namespace documents; the watcher applies good ones and
    keeps the last good set on malformed frames."""

    def test_ws_watcher_applies_pushed_namespaces(self):
        import json
        import socket
        import threading

        from keto_tpu.namespace.watcher import WsNamespaceWatcher
        from keto_tpu.utils import ws as wsmod
        from keto_tpu.utils.errors import ErrNotFound

        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(("127.0.0.1", 0))
        srv.listen(2)
        srv.settimeout(0.2)  # close() can't wake a blocked accept
        port = srv.getsockname()[1]
        conns = []
        ready = threading.Event()
        stop_serving = threading.Event()

        def serve():
            while not stop_serving.is_set():
                try:
                    sock, _ = srv.accept()
                except TimeoutError:
                    continue
                except OSError:
                    return
                conns.append(wsmod.accept(sock))
                ready.set()

        t = threading.Thread(target=serve, daemon=True)
        t.start()
        watcher = WsNamespaceWatcher(f"ws://127.0.0.1:{port}/namespaces")
        try:
            assert watcher.wait_connected(10)
            assert ready.wait(10)
            conn = conns[0]
            # push a namespace set
            conn.send_text(
                json.dumps(
                    {"namespaces": [{"id": 1, "name": "pushed"}]}
                )
            )
            deadline = time.time() + 10
            while time.time() < deadline:
                try:
                    watcher.get_namespace_by_name("pushed")
                    break
                except ErrNotFound:
                    time.sleep(0.02)
            assert watcher.get_namespace_by_name("pushed").id == 1
            # malformed frame: keep the last good set
            conn.send_text("{not json")
            conn.send_text(json.dumps([{"no_name_field": True}]))
            time.sleep(0.2)
            assert watcher.get_namespace_by_name("pushed").id == 1
            # replacement set applies
            conn.send_text(json.dumps([{"id": 7, "name": "second"}]))
            deadline = time.time() + 10
            while time.time() < deadline:
                try:
                    watcher.get_namespace_by_name("second")
                    break
                except ErrNotFound:
                    time.sleep(0.02)
            assert watcher.get_namespace_by_name("second").id == 7
            with pytest.raises(ErrNotFound):
                watcher.get_namespace_by_name("pushed")
        finally:
            watcher.close()
            stop_serving.set()
            srv.close()
            t.join(timeout=5)

    def test_config_dispatches_ws_uri(self):
        from keto_tpu.namespace.watcher import WsNamespaceWatcher

        cfg = Config(values={"namespaces": "ws://127.0.0.1:1/nope"})
        mgr = cfg.namespace_manager()
        try:
            # the swappable wrapper delegates to a ws watcher that keeps
            # retrying the (dead) endpoint without blocking construction
            assert isinstance(mgr.inner, WsNamespaceWatcher)
            assert mgr.namespaces() == []
        finally:
            mgr.inner.close()
