"""Sharded engine correctness on a multi-device mesh.

Runs only when >= 8 devices are visible (the 8-device virtual CPU mesh);
under the single-chip axon backend these skip and the subprocess wrapper
(test_sharded_subprocess.py) re-runs them with the right interpreter env.
"""

import jax
import numpy as np
import pytest

from keto_tpu.engine import CheckEngine
from keto_tpu.graph import SnapshotManager
from keto_tpu.parallel import ShardedCheckEngine, make_mesh
from keto_tpu.relationtuple import RelationTuple
from keto_tpu.store import InMemoryTupleStore

needs_mesh = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 devices (virtual CPU mesh)"
)


def t(s: str) -> RelationTuple:
    return RelationTuple.from_string(s)


def random_store(rng, n_objects, n_users, n_edges, n_rel=3):
    store = InMemoryTupleStore()
    tuples = set()
    for _ in range(n_edges):
        obj = f"o{rng.integers(n_objects)}"
        rel = f"r{rng.integers(n_rel)}"
        if rng.random() < 0.45:
            sub = f"n:o{rng.integers(n_objects)}#r{rng.integers(n_rel)}"
        else:
            sub = f"u{rng.integers(n_users)}"
        tuples.add(f"n:{obj}#{rel}@({sub})")
    store.write_relation_tuples(*(t(s) for s in tuples))
    return store


@needs_mesh
@pytest.mark.parametrize("mesh_shape", [(1, 8), (2, 4), (4, 2)])
def test_sharded_matches_oracle(mesh_shape):
    rng = np.random.default_rng(42)
    store = random_store(rng, n_objects=20, n_users=12, n_edges=300)
    mgr = SnapshotManager(store)
    data, edge = mesh_shape
    mesh = make_mesh(data=data, edge=edge)
    host = CheckEngine(store, max_depth=5)
    sharded = ShardedCheckEngine(mgr, mesh=mesh, max_depth=5)
    reqs = []
    for _ in range(96):
        obj = f"o{rng.integers(20)}"
        rel = f"r{rng.integers(3)}"
        if rng.random() < 0.3:
            sub = f"n:o{rng.integers(20)}#r{rng.integers(3)}"
        else:
            sub = f"u{rng.integers(12)}"
        reqs.append(t(f"n:{obj}#{rel}@({sub})"))
    expect = [host.subject_is_allowed(r) for r in reqs]
    got = sharded.batch_check(reqs)
    assert got == expect


@needs_mesh
def test_sharded_depth_budget_and_writes():
    store = InMemoryTupleStore()
    store.write_relation_tuples(
        t("n:obj#r@(n:s1#m)"), t("n:s1#m@(n:s2#m)"), t("n:s2#m@alice")
    )
    mgr = SnapshotManager(store)
    eng = ShardedCheckEngine(mgr, mesh=make_mesh(data=2, edge=4), max_depth=8)
    req = t("n:obj#r@alice")
    assert not eng.subject_is_allowed(req, max_depth=2)
    assert eng.subject_is_allowed(req, max_depth=3)
    # write visibility across re-shard
    store.write_relation_tuples(t("n:s2#m@bob"))
    assert eng.subject_is_allowed(t("n:obj#r@bob"))


@needs_mesh
def test_sharded_check_ids_matches_object_api():
    """The array-native path (what the batcher/array clients use) must
    agree with the object path and the host oracle."""
    rng = np.random.default_rng(43)
    store = random_store(rng, n_objects=16, n_users=10, n_edges=220)
    mgr = SnapshotManager(store)
    host = CheckEngine(store, max_depth=5)
    eng = ShardedCheckEngine(mgr, mesh=make_mesh(data=2, edge=4), max_depth=5)
    snap = mgr.snapshot()
    reqs = []
    for _ in range(64):
        obj = f"o{rng.integers(16)}"
        rel = f"r{rng.integers(3)}"
        sub = f"u{rng.integers(10)}"
        reqs.append(t(f"n:{obj}#{rel}@{sub}"))
    start = np.array(
        [snap.node_for_set(r.namespace, r.object, r.relation) for r in reqs],
        dtype=np.int64,
    )
    target = np.array(
        [snap.node_for_subject(r.subject) for r in reqs], dtype=np.int64
    )
    expect = [host.subject_is_allowed(r) for r in reqs]
    got = eng.check_ids(start, target)
    assert got.tolist() == expect
    # ids beyond the snapshot clamp to dummy -> denied, not crash
    big = np.array([snap.padded_nodes + 5], dtype=np.int64)
    assert eng.check_ids(big, big).tolist() == [False]
    assert eng.check_ids(
        np.empty(0, np.int64), np.empty(0, np.int64)
    ).tolist() == []


@needs_mesh
def test_sharded_circular_and_unknowns():
    store = InMemoryTupleStore()
    store.write_relation_tuples(t("n:a#r@(n:b#r)"), t("n:b#r@(n:a#r)"))
    mgr = SnapshotManager(store)
    eng = ShardedCheckEngine(mgr, mesh=make_mesh(data=1, edge=8))
    assert not eng.subject_is_allowed(t("n:a#r@alice"))
    assert eng.subject_is_allowed(t("n:a#r@(n:a#r)"))
    assert not eng.subject_is_allowed(t("zz:zz#zz@nobody"))


@needs_mesh
@pytest.mark.parametrize("mesh_shape", [(1, 8), (2, 4), (4, 2)])
def test_sharded_closure_matches_oracle(mesh_shape):
    """The 1B-rung engine (D replicated, CSRs node-striped): parity with
    the host oracle across mesh shapes, depths, and target kinds."""
    from keto_tpu.parallel import ShardedClosureEngine

    rng = np.random.default_rng(7)
    store = random_store(rng, n_objects=20, n_users=12, n_edges=300)
    mgr = SnapshotManager(store)
    data, edge = mesh_shape
    eng = ShardedClosureEngine(
        mgr, mesh=make_mesh(data=data, edge=edge), max_depth=5
    )
    host = CheckEngine(store, max_depth=5)
    reqs = []
    for _ in range(96):
        obj = f"o{rng.integers(20)}"
        rel = f"r{rng.integers(3)}"
        if rng.random() < 0.3:
            sub = f"n:o{rng.integers(20)}#r{rng.integers(3)}"
        else:
            sub = f"u{rng.integers(12)}"
        reqs.append(t(f"n:{obj}#{rel}@({sub})"))
    for depths in (None, [1 + (i % 5) for i in range(96)]):
        got = eng.batch_check(reqs, depths=depths)
        want = host.batch_check(reqs, depths=depths)
        assert got == want, mesh_shape
    # per-shard residency accounting exists and is positive
    bytes_ = eng.shard_bytes()
    assert bytes_["total_per_shard"] > 0
    assert set(bytes_) >= {"d_replicated", "f0_vals", "out_vals"}


@needs_mesh
def test_sharded_closure_wide_fanout_fallback():
    """Rows wider than the static gather widths overflow to the exact
    host fallback — never silently truncate."""
    from keto_tpu.parallel import ShardedClosureEngine

    store = InMemoryTupleStore()
    tuples = []
    for i in range(70):  # 70 set successors > f0_max=32
        tuples.append(t(f"n:doc#view@(n:g{i}#m)"))
        tuples.append(t(f"n:g{i}#m@(n:h{i}#m)"))
    for i in range(50):  # 50 interior in-neighbors > l_max=32
        tuples.append(t(f"n:h{i}#m@alice"))
    store.write_relation_tuples(*tuples)
    mgr = SnapshotManager(store)
    eng = ShardedClosureEngine(
        mgr, mesh=make_mesh(data=1, edge=8), max_depth=5
    )
    host = CheckEngine(store, max_depth=5)
    reqs = [
        t("n:doc#view@alice"),
        t("n:doc#view@bob"),
        t("n:doc#view@(n:g3#m)"),
        t("n:doc#view@(n:h9#m)"),
    ]
    assert eng.batch_check(reqs) == host.batch_check(reqs)


@needs_mesh
def test_sharded_closure_escalated_pass_keeps_wide_rows_on_device():
    """A wide-fanout row (user in >32 groups) must be answered by the
    ESCALATED device pass, not the host oracle (VERDICT r4 weak #6):
    host_fallback stays 0 while the escalated counter moves."""
    from keto_tpu.parallel import ShardedClosureEngine

    store = InMemoryTupleStore()
    tuples = [t("n:doc#view@(n:g0#m)")]
    for i in range(120):  # alice in 120 groups: L row way past l_max=32
        tuples.append(t(f"n:g{i}#m@alice"))
        tuples.append(t(f"n:top#r@(n:g{i}#m)"))  # make every g interior
    store.write_relation_tuples(*tuples)
    mgr = SnapshotManager(store)
    eng = ShardedClosureEngine(
        mgr, mesh=make_mesh(data=1, edge=8), max_depth=5
    )
    host = CheckEngine(store, max_depth=5)
    reqs = [
        t("n:doc#view@alice"),   # wide L row -> escalated pass
        t("n:top#r@alice"),      # wide F0 row (120 set successors)
        t("n:doc#view@mallory"),
    ]
    assert eng.batch_check(reqs) == host.batch_check(reqs) == [
        True, True, False,
    ]
    assert eng.overflow_stats["escalated"] > 0
    assert eng.overflow_stats["host_fallback"] == 0

    # beyond even the escalated width -> host oracle, still exact, counted
    eng2 = ShardedClosureEngine(
        mgr,
        mesh=make_mesh(data=1, edge=8),
        max_depth=5,
        f0_max_escalated=64,
        l_max_escalated=64,
    )
    assert eng2.batch_check(reqs) == [True, True, False]
    assert eng2.overflow_stats["host_fallback"] > 0
