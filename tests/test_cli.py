"""CLI tests: the cobra-executor e2e pattern of the reference
(internal/e2e/cli_client_test.go) — drive the click CLI against a live
server over real gRPC."""

import json

import pytest
from click.testing import CliRunner

from keto_tpu.cli import cli
from tests.test_api_server import ServerFixture
from keto_tpu.driver import Config


@pytest.fixture(scope="module")
def server():
    cfg = Config(
        values={
            "namespaces": [{"id": 1, "name": "videos"}],
            "log": {"level": "error"},
            "serve": {
                "read": {"port": 0, "host": "127.0.0.1"},
                "write": {"port": 0, "host": "127.0.0.1"},
            },
        }
    )
    s = ServerFixture(cfg)
    yield s
    s.stop()


@pytest.fixture
def runner(server):
    r = CliRunner()
    remotes = [
        "--read-remote", f"127.0.0.1:{server.read_port}",
        "--write-remote", f"127.0.0.1:{server.write_port}",
    ]
    return r, remotes


class TestCliFlow:
    def test_version(self, runner):
        r, _ = runner
        res = r.invoke(cli, ["version"])
        assert res.exit_code == 0
        assert res.output.strip()

    def test_status(self, runner):
        r, remotes = runner
        res = r.invoke(cli, remotes + ["status"])
        assert res.exit_code == 0, res.output
        assert "SERVING" in res.output

    def test_parse(self, runner):
        r, _ = runner
        res = r.invoke(
            cli,
            ["relation-tuple", "parse", "-"],
            input="// a comment\nvideos:/cats#owner@(cat lady)\n\n",
        )
        assert res.exit_code == 0, res.output
        doc = json.loads(res.output.strip())
        assert doc == {
            "namespace": "videos",
            "object": "/cats",
            "relation": "owner",
            "subject_id": "cat lady",
        }

    def test_create_check_expand_get_delete(self, runner):
        r, remotes = runner
        tuples = [
            {"namespace": "videos", "object": "/cats", "relation": "owner",
             "subject_id": "cat lady"},
            {"namespace": "videos", "object": "/cats/1.mp4", "relation": "view",
             "subject_set": {"namespace": "videos", "object": "/cats",
                              "relation": "owner"}},
        ]
        res = r.invoke(
            cli,
            remotes + ["relation-tuple", "create", "-"],
            input=json.dumps(tuples),
        )
        assert res.exit_code == 0, res.output
        assert "created 2" in res.output

        res = r.invoke(
            cli,
            remotes + ["check", "cat lady", "view", "videos", "/cats/1.mp4"],
        )
        assert res.exit_code == 0, res.output
        assert "Allowed" in res.output

        res = r.invoke(
            cli, remotes + ["check", "dog guy", "view", "videos", "/cats/1.mp4"]
        )
        assert res.exit_code == 1
        assert "Denied" in res.output

        res = r.invoke(
            cli, remotes + ["expand", "view", "videos", "/cats/1.mp4"]
        )
        assert res.exit_code == 0, res.output
        assert "cat lady" in res.output

        res = r.invoke(
            cli,
            remotes + ["relation-tuple", "get", "--namespace", "videos",
                        "--format", "json"],
        )
        assert res.exit_code == 0, res.output
        listing = json.loads(res.output)
        assert len(listing["relation_tuples"]) == 2

        res = r.invoke(
            cli,
            remotes + ["relation-tuple", "delete-all", "--namespace", "videos",
                        "--force"],
        )
        assert res.exit_code == 0, res.output
        res = r.invoke(
            cli,
            remotes + ["relation-tuple", "get", "--namespace", "videos",
                        "--format", "json"],
        )
        assert json.loads(res.output)["relation_tuples"] == []

    def test_namespace_validate(self, runner, tmp_path):
        r, _ = runner
        good = tmp_path / "ns.yml"
        good.write_text("- name: videos\n  id: 1\n")
        bad = tmp_path / "bad.yml"
        bad.write_text("- nope: x\n")
        res = r.invoke(cli, ["namespace", "validate", str(good)])
        assert res.exit_code == 0, res.output
        res = r.invoke(cli, ["namespace", "validate", str(bad)])
        assert res.exit_code == 1

    def test_migrate_status_up_flow(self, tmp_path):
        r = CliRunner()
        cfg = tmp_path / "keto.yml"
        cfg.write_text(
            f"dsn: sqlite://{tmp_path}/keto.db\nnamespaces: []\n"
        )
        # fresh DB: everything pending (migrate commands never auto-apply)
        res = r.invoke(cli, ["migrate", "status", "-c", str(cfg)])
        assert res.exit_code == 0, res.output
        assert "pending" in res.output
        res = r.invoke(cli, ["migrate", "up", "-c", str(cfg), "--yes"])
        assert res.exit_code == 0, res.output
        assert "applied" in res.output
        res = r.invoke(cli, ["migrate", "status", "-c", str(cfg)])
        assert "pending" not in res.output
        res = r.invoke(cli, ["migrate", "down", "1", "-c", str(cfg), "--yes"])
        assert res.exit_code == 0, res.output
        res = r.invoke(cli, ["migrate", "status", "-c", str(cfg)])
        assert "pending" in res.output

    def test_connection_error(self, runner):
        r, _ = runner
        res = r.invoke(
            cli,
            ["--read-remote", "127.0.0.1:1", "status"],
        )
        assert res.exit_code != 0
        assert "cannot connect" in res.output
