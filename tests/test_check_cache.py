"""Check-result cache: hot single checks skip the engine; any advance of
the ANSWERING version empties the cache (the reference lists caching as
planned/unimplemented — docs/docs/implemented-planned-features.mdx:30-34)."""

from keto_tpu.driver.factory import new_test_registry
from keto_tpu.engine.cache import CheckResultCache
from keto_tpu.relationtuple import RelationTuple


def t(s: str) -> RelationTuple:
    return RelationTuple.from_string(s)


class _CountingEngine:
    """Spy wrapping an engine, counting batch_check invocations."""

    def __init__(self, inner):
        self.inner = inner
        self.calls = 0

    def __getattr__(self, name):
        return getattr(self.inner, name)

    def batch_check(self, *a, **kw):
        self.calls += 1
        return self.inner.batch_check(*a, **kw)


class TestCacheUnit:
    def test_lru_eviction_and_version_clear(self):
        c = CheckResultCache(capacity=2)
        c.put(1, "a", True)  # version mismatch before any get: dropped
        assert c.get(1, "a") is None  # also pins version 1
        c.put(1, "a", True)
        c.put(1, "b", False)
        assert c.get(1, "a") is True
        c.put(1, "c", True)  # evicts LRU ("b": "a" was touched)
        assert c.get(1, "b") is None
        assert c.get(1, "a") is True
        # version advance clears everything
        assert c.get(2, "a") is None
        assert len(c) == 0


class TestCacheServing:
    def test_hot_check_skips_engine_until_write(self):
        reg = new_test_registry(namespaces=("videos",))
        store = reg.store()
        store.write_relation_tuples(t("videos:o#r@alice"))
        checker = reg.checker()
        spy = _CountingEngine(reg.check_engine())
        checker.engine = spy

        assert checker.check(t("videos:o#r@alice"), 0) is True
        calls_after_first = spy.calls
        for _ in range(5):
            assert checker.check(t("videos:o#r@alice"), 0) is True
        assert spy.calls == calls_after_first  # all cache hits

        # different depth is a different key
        checker.check(t("videos:o#r@alice"), 3)
        assert spy.calls == calls_after_first + 1

        # a write advances the version: cache must empty, fresh answer
        store.write_relation_tuples(t("videos:o#r@bob"))
        assert checker.check(t("videos:o#r@bob"), 0) is True
        assert spy.calls > calls_after_first + 1
        reg._batcher.close()

    def test_delete_invalidates_cached_allow(self):
        reg = new_test_registry(namespaces=("videos",))
        store = reg.store()
        store.write_relation_tuples(t("videos:o#r@alice"))
        checker = reg.checker()
        assert checker.check(t("videos:o#r@alice"), 0) is True
        store.delete_relation_tuples(t("videos:o#r@alice"))
        assert checker.check(t("videos:o#r@alice"), 0) is False
        reg._batcher.close()

    def test_cache_metrics_exposed(self):
        reg = new_test_registry(namespaces=("videos",))
        reg.store().write_relation_tuples(t("videos:o#r@alice"))
        checker = reg.checker()
        checker.check(t("videos:o#r@alice"), 0)
        checker.check(t("videos:o#r@alice"), 0)
        text = reg.metrics().expose()
        assert "keto_check_cache_hits_total 1" in text
        reg._batcher.close()

    def test_bounded_freshness_cache_hits_do_not_starve_rebuild(self):
        """Under bounded freshness a cached allow must still converge
        after a revoking write even if every request hits the cache —
        answering_version kicks the background rebuild on staleness."""
        import time

        reg = new_test_registry(
            namespaces=("videos",),
            values={
                "engine": {"freshness": "bounded", "rebuild_debounce_ms": 0}
            },
        )
        store = reg.store()
        store.write_relation_tuples(t("videos:o#r@alice"))
        checker = reg.checker()
        assert checker.check(t("videos:o#r@alice"), 0) is True
        store.delete_relation_tuples(t("videos:o#r@alice"))
        deadline = time.monotonic() + 15
        got = True
        while time.monotonic() < deadline:
            got = checker.check(t("videos:o#r@alice"), 0)
            if got is False:
                break
            time.sleep(0.02)
        assert got is False
        reg._batcher.close()

    def test_cache_disabled_by_config(self):
        reg = new_test_registry(
            namespaces=("videos",), values={"engine": {"cache_size": 0}}
        )
        reg.store().write_relation_tuples(t("videos:o#r@alice"))
        checker = reg.checker()
        assert checker.cache is None
        assert checker.check(t("videos:o#r@alice"), 0) is True
        reg._batcher.close()


def test_toobig_fallback_answers_stamp_live_version():
    """A snapshot whose interior exceeds the closure limit routes checks
    to the live-store fallback; cached answers must invalidate on EVERY
    write (stamp = store version), even under bounded freshness."""
    reg = new_test_registry(
        namespaces=("videos",),
        values={
            "engine": {
                "interior_limit": 2,
                "freshness": "bounded",
                "rebuild_debounce_ms": 0,
            }
        },
    )
    store = reg.store()
    # > 2 interior nodes: closure falls back for the whole snapshot
    store.write_relation_tuples(
        t("videos:a#r@(videos:b#r)"),
        t("videos:b#r@(videos:c#r)"),
        t("videos:c#r@(videos:d#r)"),
        t("videos:d#r@alice"),
    )
    checker = reg.checker()
    assert checker.check(t("videos:d#r@alice"), 0) is True
    store.delete_relation_tuples(t("videos:d#r@alice"))
    # fallback reads the live store: the revocation must be visible on
    # the very next check, not after a rebuild window
    assert checker.check(t("videos:d#r@alice"), 0) is False
    reg._batcher.close()


def test_closed_batcher_refuses_even_cached_keys():
    import pytest

    from keto_tpu.engine.batcher import BatcherClosed

    reg = new_test_registry(namespaces=("videos",))
    reg.store().write_relation_tuples(t("videos:o#r@alice"))
    checker = reg.checker()
    assert checker.check(t("videos:o#r@alice"), 0) is True
    reg._batcher.close()
    with pytest.raises(BatcherClosed):
        checker.check(t("videos:o#r@alice"), 0)


def test_snaptoken_consistency_waits_for_rebuild():
    """gRPC CheckRequest.snaptoken (at-least-as-fresh) is real: under
    bounded freshness, a check carrying the write's snaptoken must
    reflect that write, while a plain check may serve the older
    snapshot (reference documents the field as not implemented,
    check_service.proto:43-80)."""
    reg = new_test_registry(
        namespaces=("videos",),
        values={
            "engine": {"freshness": "bounded", "rebuild_debounce_ms": 0}
        },
    )
    store = reg.store()
    store.write_relation_tuples(t("videos:o#r@alice"))
    checker = reg.checker()
    assert checker.check(t("videos:o#r@alice"), 0) is True

    store.write_relation_tuples(t("videos:o#r@bob"))
    token = store.version
    # consistency-pinned check: must see bob immediately
    assert checker.check(t("videos:o#r@bob"), 0, min_version=token) is True
    reg._batcher.close()


def test_grpc_snaptoken_and_latest_fields():
    import grpc

    from keto_tpu.api import acl_pb2, check_service_pb2
    from keto_tpu.api.services import CheckServiceStub
    from tests.test_api_server import ServerFixture

    reg = new_test_registry(
        namespaces=("videos",),
        values={
            "engine": {"freshness": "bounded", "rebuild_debounce_ms": 0}
        },
    )
    s = ServerFixture(reg)
    try:
        store = reg.store()
        store.write_relation_tuples(t("videos:o#r@alice"))
        with grpc.insecure_channel(f"127.0.0.1:{s.read_port}") as ch:
            stub = CheckServiceStub(ch)

            def check(sub, **kw):
                return stub.Check(
                    check_service_pb2.CheckRequest(
                        namespace="videos", object="o", relation="r",
                        subject=acl_pb2.Subject(id=sub), **kw,
                    )
                )

            # server may have warmed on the empty store: pin the first
            # check to the write's version (the contract under test)
            assert check("alice", snaptoken=str(store.version)).allowed
            store.write_relation_tuples(t("videos:o#r@bob"))
            token = str(store.version)
            resp = check("bob", snaptoken=token)
            assert resp.allowed and int(resp.snaptoken) >= int(token)
            store.write_relation_tuples(t("videos:o#r@carol"))
            assert check("carol", latest=True).allowed
            # malformed snaptoken -> INVALID_ARGUMENT
            import pytest

            with pytest.raises(grpc.RpcError) as e:
                check("alice", snaptoken="not-a-number")
            assert e.value.code() == grpc.StatusCode.INVALID_ARGUMENT
    finally:
        s.stop()


def test_rest_snaptoken_and_latest_params():
    """REST /check honors snaptoken/latest the same way gRPC does (a
    keto_tpu extension — the reference REST surface has neither)."""
    import httpx

    from tests.test_api_server import ServerFixture

    reg = new_test_registry(
        namespaces=("videos",),
        values={"engine": {"freshness": "bounded", "rebuild_debounce_ms": 0}},
    )
    s = ServerFixture(reg)
    try:
        store = reg.store()
        store.write_relation_tuples(t("videos:o#r@alice"))
        base = f"http://127.0.0.1:{s.read_port}/check"

        def check(sub, **extra):
            return httpx.get(
                base,
                params={
                    "namespace": "videos", "object": "o", "relation": "r",
                    "subject_id": sub, **extra,
                },
            )

        # server warmed up on the empty store: a PLAIN check may serve
        # that older snapshot under bounded freshness — pinning with the
        # write's snaptoken is exactly what forces the catch-up
        assert check("alice", snaptoken=str(store.version)).status_code == 200
        store.write_relation_tuples(t("videos:o#r@bob"))
        token = str(store.version)
        assert check("bob", snaptoken=token).status_code == 200
        store.write_relation_tuples(t("videos:o#r@carol"))
        assert check("carol", latest="true").status_code == 200
        assert check("alice", snaptoken="junk!").status_code == 400
    finally:
        s.stop()


def test_batch_consistency_both_transports():
    """Batch checks honor snaptoken/latest on both transports via the
    shipped clients (proto BatchCheckRequest fields + REST query params)."""
    from keto_tpu.client import GrpcClient, RestClient
    from tests.test_api_server import ServerFixture

    reg = new_test_registry(
        namespaces=("videos",),
        values={"engine": {"freshness": "bounded", "rebuild_debounce_ms": 0}},
    )
    s = ServerFixture(reg)
    try:
        store = reg.store()
        store.write_relation_tuples(t("videos:o#r@alice"))
        token = str(store.version)
        with RestClient(f"http://127.0.0.1:{s.read_port}") as rc:
            assert rc.batch_check(
                ["videos:o#r@alice", "videos:o#r@nobody"], snaptoken=token
            ) == [True, False]
        store.write_relation_tuples(t("videos:o#r@bob"))
        with GrpcClient(f"127.0.0.1:{s.read_port}") as gc:
            assert gc.batch_check(
                ["videos:o#r@bob", "videos:o#r@nobody"], latest=True
            ) == [True, False]
    finally:
        s.stop()
