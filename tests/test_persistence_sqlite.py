"""SQLite backend specifics: migrations, durability, network isolation.
(The full Manager contract suite in test_store.py already runs against this
backend via the parametrized `store` fixture.)"""

import pytest

from keto_tpu.namespace import MemoryNamespaceManager
from keto_tpu.persistence import SQLiteTupleStore
from keto_tpu.relationtuple import RelationQuery, RelationTuple, SubjectID


@pytest.fixture
def nsmgr():
    m = MemoryNamespaceManager()
    m.add("n")
    return m


def t(s):
    return RelationTuple.from_string(s)


class TestMigrations:
    def test_fresh_db_migrates_up(self, tmp_path, nsmgr):
        s = SQLiteTupleStore(str(tmp_path / "m.db"), namespace_manager=nsmgr)
        status = s.migrator.status()
        assert len(status) >= 2
        assert all(m.applied for m in status)
        assert not s.migrator.has_pending()
        s.close()

    def test_status_before_migrate(self, tmp_path, nsmgr):
        s = SQLiteTupleStore(
            str(tmp_path / "m.db"), namespace_manager=nsmgr, auto_migrate=False
        )
        assert s.migrator.has_pending()
        assert all(not m.applied for m in s.migrator.status())
        ran = s.migrator.up()
        assert len(ran) >= 2
        assert not s.migrator.has_pending()
        s.close()

    def test_failing_migration_rolls_back_completely(self, tmp_path):
        """A failing multi-statement migration must leave no partial DDL and
        no version row (executescript would have committed implicitly)."""
        import sqlite3

        from keto_tpu.persistence.migrator import Migrator

        mdir = tmp_path / "migrations"
        mdir.mkdir()
        (mdir / "001_bad.up.sql").write_text(
            "CREATE TABLE good_one (id INTEGER PRIMARY KEY);\n"
            "CREATE TABLE bad one (syntax error here;\n"
        )
        (mdir / "001_bad.down.sql").write_text("DROP TABLE good_one;\n")
        conn = sqlite3.connect(str(tmp_path / "rb.db"))
        m = Migrator(conn, str(mdir))
        with pytest.raises(sqlite3.OperationalError):
            m.up()
        # the first statement's table must have been rolled back
        tables = {
            r[0]
            for r in conn.execute(
                "SELECT name FROM sqlite_master WHERE type='table'"
            )
        }
        assert "good_one" not in tables
        assert m.applied_versions() == set()
        conn.close()

    def test_down_then_up_roundtrip(self, tmp_path, nsmgr):
        s = SQLiteTupleStore(str(tmp_path / "m.db"), namespace_manager=nsmgr)
        n_all = len(s.migrator.status())
        assert len(s.migrator.down(steps=n_all)) == n_all
        assert s.migrator.has_pending()
        s.migrator.up()
        s.write_relation_tuples(t("n:o#r@alice"))
        assert len(s) == 1
        s.close()


class TestDurability:
    def test_tuples_and_version_survive_reopen(self, tmp_path, nsmgr):
        path = str(tmp_path / "d.db")
        s = SQLiteTupleStore(path, namespace_manager=nsmgr, network_id="net")
        s.write_relation_tuples(t("n:o#r@alice"), t("n:o#r@bob"))
        s.delete_relation_tuples(t("n:o#r@bob"))
        v = s.version
        assert v == 2
        s.close()

        s2 = SQLiteTupleStore(path, namespace_manager=nsmgr, network_id="net")
        assert s2.version == v  # durable snaptoken
        tuples, version = s2.snapshot()
        assert tuples == [t("n:o#r@alice")]
        assert version == v
        s2.close()


class TestDefaultNetworkDurability:
    def test_server_path_without_explicit_nid_survives_restart(self, tmp_path, nsmgr):
        # the serve path passes no network_id; the store must adopt the
        # database's network on reopen (reference determineNetwork,
        # registry_default.go:207-225)
        path = str(tmp_path / "srv.db")
        s = SQLiteTupleStore(path, namespace_manager=nsmgr)
        s.write_relation_tuples(t("n:o#r@alice"))
        nid = s.network_id
        s.close()
        s2 = SQLiteTupleStore(path, namespace_manager=nsmgr)
        assert s2.network_id == nid
        assert s2.all_tuples() == [t("n:o#r@alice")]
        assert s2.version == 1
        s2.close()


class TestIsolation:
    def test_two_networks_one_database(self, tmp_path, nsmgr):
        # reference manager_isolation.go:44-138: two persisters with
        # different nids over one database must not see each other
        path = str(tmp_path / "iso.db")
        s1 = SQLiteTupleStore(path, namespace_manager=nsmgr, network_id="n1")
        s2 = SQLiteTupleStore(path, namespace_manager=nsmgr, network_id="n2")
        s1.write_relation_tuples(t("n:o#r@alice"))
        s2.write_relation_tuples(t("n:o#r@bob"))
        assert s1.get_relation_tuples(RelationQuery(namespace="n"))[0] == [
            t("n:o#r@alice")
        ]
        assert s2.get_relation_tuples(RelationQuery(namespace="n"))[0] == [
            t("n:o#r@bob")
        ]
        # independent version counters per network
        assert s1.version == 1
        assert s2.version == 1
        s1.close()
        s2.close()


class TestDeviceIntegration:
    def test_snapshot_manager_over_sqlite(self, tmp_path, nsmgr):
        from keto_tpu.engine.device import DeviceCheckEngine
        from keto_tpu.graph import SnapshotManager

        s = SQLiteTupleStore(str(tmp_path / "g.db"), namespace_manager=nsmgr)
        s.write_relation_tuples(
            t("n:obj#access@(n:org#member)"), t("n:org#member@alice")
        )
        mgr = SnapshotManager(s)
        dev = DeviceCheckEngine(mgr)
        assert dev.subject_is_allowed(t("n:obj#access@alice"))
        assert not dev.subject_is_allowed(t("n:obj#access@bob"))
        # incremental write-through
        s.write_relation_tuples(t("n:org#member@carol"))
        assert dev.subject_is_allowed(t("n:obj#access@carol"))
        s.close()
