"""List-serving parity: the reverse-index answers (engine/listing.py) must
be byte-identical to a brute-force forward-scan oracle — one fallback check
per candidate — on every graph (cycles, unicode vocab, subject-set
indirections), through both the host and device closure engines, across
page boundaries with writes landing between pages, and through the REST
and gRPC surfaces. Plus the breaker drill: injected gather failures must
fall back to the oracle with identical results and open the breaker."""

import time

import grpc
import numpy as np
import pytest

from keto_tpu.client import GrpcClient, RestClient
from keto_tpu.engine import CheckEngine
from keto_tpu.engine.closure import ClosureCheckEngine
from keto_tpu.engine.listing import ListEngine
from keto_tpu.engine.paging import encode_page_token
from keto_tpu.faults import FAULTS
from keto_tpu.graph import SnapshotManager
from keto_tpu.relationtuple import RelationTuple, SubjectID, SubjectSet
from keto_tpu.store import InMemoryTupleStore
from keto_tpu.utils.errors import ErrMalformedPageToken, ErrStalePageToken
from tests.test_api_server import ServerFixture
from tests.test_device_engines import random_store


def t(s: str) -> RelationTuple:
    return RelationTuple.from_string(s)


DEPTH = 5


@pytest.fixture(params=["host", "device"])
def query_mode(request):
    return request.param


def make_list_engine(store, query_mode, **kw):
    eng = ClosureCheckEngine(
        SnapshotManager(store),
        max_depth=DEPTH,
        freshness="strong",
        rebuild_debounce_s=0.0,
        query_mode=query_mode,
    )
    return eng, ListEngine(eng, **kw)


def store_vocab(store):
    """Every (namespace, object) and subject id the store mentions —
    the candidate universe the brute-force oracle scans."""
    from keto_tpu.relationtuple import RelationQuery
    from keto_tpu.utils.pagination import PaginationOptions

    objects, rels, sids = set(), set(), set()
    token = ""
    while True:
        batch, token = store.get_relation_tuples(
            RelationQuery(), PaginationOptions(token=token)
        )
        for tp in batch:
            objects.add((tp.namespace, tp.object))
            rels.add(tp.relation)
            if isinstance(tp.subject, SubjectSet):
                objects.add((tp.subject.namespace, tp.subject.object))
                rels.add(tp.subject.relation)
            else:
                sids.add(tp.subject.id)
        if not token:
            break
    return sorted(objects), sorted(rels), sorted(sids)


def oracle_objects(store, subject, relation, namespace):
    """Forward-scan oracle: one independent host-BFS check per candidate
    object (deliberately NOT listing.py's internal oracle)."""
    chk = CheckEngine(store, max_depth=DEPTH)
    objects, _, _ = store_vocab(store)
    return sorted(
        o
        for ns, o in objects
        if ns == namespace
        and chk.subject_is_allowed(
            RelationTuple(namespace, o, relation, subject), DEPTH
        )
    )


def oracle_subjects(store, namespace, object, relation):
    chk = CheckEngine(store, max_depth=DEPTH)
    _, _, sids = store_vocab(store)
    return sorted(
        s
        for s in sids
        if chk.subject_is_allowed(
            RelationTuple(namespace, object, relation, SubjectID(s)), DEPTH
        )
    )


def all_items(le, kind, *args, page_size=0):
    """Drain every page; returns (items, sources)."""
    items, sources, token = [], [], ""
    fn = le.list_objects if kind == "objects" else le.list_subjects
    while True:
        page = fn(*args, max_depth=DEPTH, page_size=page_size,
                  page_token=token)
        items.extend(page.items)
        sources.append(page.source)
        token = page.next_page_token
        if not token:
            break
    return items, sources


class TestReverseParityRandom:
    """Random graphs (cycles + ~45% subject-set indirections) — the same
    generator the device-engine parity suite trusts."""

    @pytest.mark.parametrize("seed", range(4))
    def test_list_objects_matches_oracle(self, query_mode, seed):
        rng = np.random.default_rng(seed)
        store = random_store(rng, n_objects=15, n_users=10, n_edges=120)
        _, le = make_list_engine(store, query_mode)
        for rel in ("r0", "r1", "r2"):
            for sub in (
                SubjectID("u3"),
                SubjectID("u7"),
                SubjectSet("n", "o2", "r1"),
                SubjectID("nobody"),
            ):
                got = le.list_objects(sub, rel, "n", max_depth=DEPTH)
                want = oracle_objects(store, sub, rel, "n")
                assert got.items == want, (
                    f"seed={seed} mode={query_mode} rel={rel} sub={sub}"
                )
        assert le.n_oracle == 0, "reverse path declined on a resident closure"

    @pytest.mark.parametrize("seed", range(4))
    def test_list_subjects_matches_oracle(self, query_mode, seed):
        rng = np.random.default_rng(seed + 50)
        store = random_store(rng, n_objects=12, n_users=8, n_edges=100)
        _, le = make_list_engine(store, query_mode)
        for o in range(0, 12, 3):
            for rel in ("r0", "r2"):
                got = le.list_subjects("n", f"o{o}", rel, max_depth=DEPTH)
                want = oracle_subjects(store, "n", f"o{o}", rel)
                assert got.items == want, (
                    f"seed={seed} mode={query_mode} o=o{o} rel={rel}"
                )
        assert le.n_oracle == 0


class TestReverseParityShapes:
    def test_cycle(self, query_mode):
        store = InMemoryTupleStore()
        store.write_relation_tuples(
            t("n:a#r@(n:b#r)"),
            t("n:b#r@(n:a#r)"),
            t("n:b#r@alice"),
            t("n:c#r@(n:a#r)"),
        )
        _, le = make_list_engine(store, query_mode)
        got = le.list_objects(SubjectID("alice"), "r", "n", max_depth=DEPTH)
        assert got.items == ["a", "b", "c"]
        assert got.items == oracle_objects(store, SubjectID("alice"), "r", "n")
        assert le.list_subjects("n", "c", "r", max_depth=DEPTH).items == [
            "alice"
        ]

    def test_unicode_vocab(self, query_mode):
        store = InMemoryTupleStore()
        store.write_relation_tuples(
            RelationTuple("n", "café", "läsa", SubjectID("żółć")),
            RelationTuple(
                "n", "naïve/文档", "läsa", SubjectSet("n", "café", "läsa")
            ),
            RelationTuple("n", "café", "läsa", SubjectID("ピカチュウ")),
        )
        _, le = make_list_engine(store, query_mode)
        for sid in ("żółć", "ピカチュウ"):
            got = le.list_objects(SubjectID(sid), "läsa", "n", max_depth=DEPTH)
            assert got.items == ["café", "naïve/文档"]
            assert got.items == oracle_objects(
                store, SubjectID(sid), "läsa", "n"
            )
        assert le.list_subjects("n", "naïve/文档", "läsa",
                                max_depth=DEPTH).items == [
            "żółć", "ピカチュウ"
        ]

    def test_namespaces_do_not_leak(self, query_mode):
        store = InMemoryTupleStore()
        store.write_relation_tuples(
            t("n:doc#view@alice"), t("m:doc2#view@alice")
        )
        _, le = make_list_engine(store, query_mode)
        assert le.list_objects(
            SubjectID("alice"), "view", "n", max_depth=DEPTH
        ).items == ["doc"]
        assert le.list_objects(
            SubjectID("alice"), "view", "m", max_depth=DEPTH
        ).items == ["doc2"]


class TestPaging:
    def seeded(self, query_mode):
        store = InMemoryTupleStore()
        store.write_relation_tuples(
            *(t(f"n:doc{i:02d}#view@alice") for i in range(9)),
            t("n:hub#view@(n:doc03#view)"),
        )
        return store, make_list_engine(store, query_mode)

    def test_paged_equals_unpaged(self, query_mode):
        store, (_, le) = self.seeded(query_mode)
        full = le.list_objects(
            SubjectID("alice"), "view", "n", max_depth=DEPTH
        ).items
        assert full == oracle_objects(store, SubjectID("alice"), "view", "n")
        for size in (1, 3, 4):
            items, _ = all_items(
                le, "objects", SubjectID("alice"), "view", "n",
                page_size=size,
            )
            assert items == full, f"page_size={size}"

    def test_write_between_pages_is_stale(self, query_mode):
        store, (_, le) = self.seeded(query_mode)
        page = le.list_objects(
            SubjectID("alice"), "view", "n", max_depth=DEPTH, page_size=4
        )
        assert page.next_page_token
        store.write_relation_tuples(t("n:zzz#view@alice"))
        with pytest.raises(ErrStalePageToken) as ei:
            le.list_objects(
                SubjectID("alice"), "view", "n",
                max_depth=DEPTH, page_token=page.next_page_token,
            )
        assert ei.value.status_code == 409
        # a fresh (token-free) query serves the new version, new item seen
        fresh = le.list_objects(
            SubjectID("alice"), "view", "n", max_depth=DEPTH
        )
        assert "zzz" in fresh.items

    def test_cross_engine_token_rejected(self, query_mode):
        _, (_, le) = self.seeded(query_mode)
        page = le.list_objects(
            SubjectID("alice"), "view", "n", max_depth=DEPTH
        )
        alien = encode_page_token("expand", page.version, {"o": 0})
        with pytest.raises(ErrMalformedPageToken) as ei:
            le.list_objects(
                SubjectID("alice"), "view", "n",
                max_depth=DEPTH, page_token=alien,
            )
        assert ei.value.status_code == 400
        with pytest.raises(ErrMalformedPageToken):
            le.list_objects(
                SubjectID("alice"), "view", "n",
                max_depth=DEPTH, page_token="!!garbage!!",
            )


class TestBreakerDrill:
    def test_gather_failures_fall_back_then_open(self):
        store = InMemoryTupleStore()
        store.write_relation_tuples(
            t("n:doc1#view@alice"),
            t("n:doc2#view@(n:doc1#view)"),
        )
        eng, le = make_list_engine(
            store, "host", breaker_threshold=3, breaker_cooldown_s=0.2
        )
        want = le.list_objects(
            SubjectID("alice"), "view", "n", max_depth=DEPTH
        )
        assert want.source == "reverse"
        try:
            FAULTS.arm("list.gather_fail", times=3)
            for i in range(3):
                page = le.list_objects(
                    SubjectID("alice"), "view", "n", max_depth=DEPTH
                )
                # the oracle answer is byte-identical to the reverse one
                assert page.source == "oracle", f"call {i}"
                assert page.items == want.items
            assert le.breaker_open()
            assert le.n_reverse_failures == 3
            # breaker open: served by the oracle without touching reverse
            page = le.list_objects(
                SubjectID("alice"), "view", "n", max_depth=DEPTH
            )
            assert page.source == "oracle"
            assert page.items == want.items
        finally:
            FAULTS.reset()
        time.sleep(0.25)
        healed = le.list_objects(
            SubjectID("alice"), "view", "n", max_depth=DEPTH
        )
        assert healed.source == "reverse"
        assert healed.items == want.items


@pytest.fixture(scope="module")
def server():
    from keto_tpu.driver import Config

    cfg = Config(
        values={
            "namespaces": [{"id": 1, "name": "n"}],
            "log": {"level": "error"},
            "serve": {
                "read": {"port": 0, "host": "127.0.0.1"},
                "write": {"port": 0, "host": "127.0.0.1"},
            },
        }
    )
    s = ServerFixture(cfg)
    with RestClient(
        f"http://127.0.0.1:{s.read_port}",
        f"http://127.0.0.1:{s.write_port}",
    ) as c:
        c.create_relation_tuple("n:doc1#viewer@alice")
        c.create_relation_tuple("n:doc2#viewer@alice")
        c.create_relation_tuple("n:doc1#viewer@bob")
        c.create_relation_tuple("n:doc3#viewer@(n:doc1#viewer)")
    yield s
    s.stop()


class TestRestSurface:
    def test_list_objects_and_subjects(self, server):
        with RestClient(f"http://127.0.0.1:{server.read_port}") as c:
            res = c.list_objects("alice", "viewer", "n")
            assert res.items == ["doc1", "doc2", "doc3"]
            assert res.snaptoken
            assert c.list_subjects("n", "doc1", "viewer").items == [
                "alice", "bob"
            ]
            # doc3 grants ride the doc1#viewer indirection
            assert c.list_subjects("n", "doc3", "viewer").items == [
                "alice", "bob"
            ]

    def test_paging_round_trip(self, server):
        with RestClient(f"http://127.0.0.1:{server.read_port}") as c:
            first = c.list_objects("alice", "viewer", "n", page_size=2)
            assert len(first.items) == 2 and first.next_page_token
            rest_ = c.list_objects(
                "alice", "viewer", "n",
                page_size=2, page_token=first.next_page_token,
            )
            assert first.items + rest_.items == ["doc1", "doc2", "doc3"]

    def test_missing_params_400(self, server):
        import httpx

        with httpx.Client(
            base_url=f"http://127.0.0.1:{server.read_port}", timeout=30
        ) as c:
            r = c.get(
                "/relation-tuples/list-objects",
                params={"namespace": "n", "relation": "viewer"},
            )
            assert r.status_code == 400  # no subject
            r = c.get(
                "/relation-tuples/list-subjects",
                params={"namespace": "n", "relation": "viewer"},
            )
            assert r.status_code == 400  # no object

    def test_stale_token_409(self, server):
        with RestClient(
            f"http://127.0.0.1:{server.read_port}",
            f"http://127.0.0.1:{server.write_port}",
        ) as c:
            first = c.list_objects("alice", "viewer", "n", page_size=2)
            assert first.next_page_token
            c.create_relation_tuple("n:stale-probe#viewer@alice")
            with pytest.raises(ErrStalePageToken):
                c.list_objects(
                    "alice", "viewer", "n",
                    page_size=2, page_token=first.next_page_token,
                )


class TestGrpcSurface:
    def test_list_round_trip(self, server):
        with GrpcClient(f"127.0.0.1:{server.read_port}") as c:
            res = c.list_objects("alice", "viewer", "n")
            assert "doc1" in res.items and "doc2" in res.items
            subs = c.list_subjects("n", "doc1", "viewer")
            assert subs.items == ["alice", "bob"]
            assert res.snaptoken

    def test_stale_token_failed_precondition(self, server):
        with GrpcClient(f"127.0.0.1:{server.read_port}") as c:
            first = c.list_objects("alice", "viewer", "n", page_size=1)
            token = first.next_page_token
            assert token
        with RestClient(
            f"http://127.0.0.1:{server.read_port}",
            f"http://127.0.0.1:{server.write_port}",
        ) as w:
            w.create_relation_tuple("n:grpc-stale#viewer@alice")
        with GrpcClient(f"127.0.0.1:{server.read_port}") as c:
            with pytest.raises(grpc.RpcError) as ei:
                c.list_objects(
                    "alice", "viewer", "n", page_size=1, page_token=token
                )
            assert ei.value.code() == grpc.StatusCode.FAILED_PRECONDITION
