"""The chaos soak as a pytest tier (slow-marked; tools/check.sh runs the
same thing directly as its own gate). One seeded run of the in-process
engine phase plus the forked pool phase; the harness's own invariants
(answer parity, snaptoken monotonicity, no lost futures, bounded p99,
pool convergence after drop/crash faults) are the assertions."""

import json
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_soak(*args: str) -> dict:
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "soak.py"), *args],
        capture_output=True,
        text=True,
        timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        cwd=_REPO,
    )
    assert proc.returncode == 0, (
        f"soak exited {proc.returncode}\nstdout:\n{proc.stdout[-4000:]}\n"
        f"stderr:\n{proc.stderr[-4000:]}"
    )
    return json.loads(proc.stdout)


def test_smoke_soak_invariants_hold():
    doc = _run_soak("--smoke", "--seed", "4", "--pool")
    assert doc["ok"] is True
    engine = doc["phases"][0]
    assert engine["violations"] == []
    assert engine["timeouts"] == 0
    assert engine["parity_mismatches"] == 0
    assert len(engine["faults_injected"]) >= 3  # the schedule really ran
    pool = doc["phases"][1]
    assert pool["violations"] == []
    assert pool["respawns"] >= 1  # inherited replica.crash healed


def test_soak_schedule_is_deterministic_per_seed():
    a = _run_soak("--smoke", "--seed", "11", "--ops", "200", "--writes",
                  "20", "--faults", "3")
    b = _run_soak("--smoke", "--seed", "11", "--ops", "200", "--writes",
                  "20", "--faults", "3")
    assert (
        a["phases"][0]["faults_injected"]
        == b["phases"][0]["faults_injected"]
    )
