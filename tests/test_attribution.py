"""Performance-attribution plane e2e (PR 7): cross-process trace
propagation (client-minted W3C traceparent -> server spans, flight
records, exemplars — hedged duplicates included), the wall-clock
accounting ledger behind /debug/attribution and
keto_time_attribution_seconds_total (conservation: stages must sum to the
measured wall time), and the stdlib sampling profiler behind
/debug/pprof + tools/flame.py."""

import importlib.util
import os
import re
import threading
import time

import grpc
import httpx
import pytest

from keto_tpu.driver import Config
from keto_tpu.telemetry.attribution import (
    ATTRIBUTION_STAGES,
    UNATTRIBUTED,
    AttributionLedger,
    TimeLedger,
    current_ledger,
    ledger_mark,
    reset_current_ledger,
    set_current_ledger,
)
from keto_tpu.telemetry.tracing import (
    SpanContext,
    current_traceparent,
    format_traceparent,
    mint_traceparent,
    parse_traceparent,
)
from tests.test_api_server import ServerFixture

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def server():
    cfg = Config(
        values={
            "namespaces": [{"id": 1, "name": "videos"}],
            "serve": {
                "read": {"port": 0, "host": "127.0.0.1"},
                "write": {"port": 0, "host": "127.0.0.1"},
            },
            "log": {"level": "error"},
            # slow_ms 0: EVERY check is flight-recorded, so the tests can
            # join client trace ids against /debug/flight deterministically
            "telemetry": {"flight": {"slow_ms": 0}},
        },
        env={},
    )
    s = ServerFixture(cfg)
    yield s
    s.stop()


def _trace_id_of(traceparent: str) -> str:
    return traceparent.split("-")[1]


def _debug(server, path: str, **params):
    return httpx.get(
        f"http://127.0.0.1:{server.read_port}{path}",
        params=params,
        timeout=30,
    )


def _flight_trace_ids(server) -> dict:
    """trace_id -> list of flight records carrying it."""
    out: dict = {}
    for rec in _debug(server, "/debug/flight", n=500).json()["records"]:
        tid = rec.get("trace_id")
        if tid:
            out.setdefault(tid, []).append(rec)
    return out


def _span_trace_ids(server) -> set:
    return {
        s["trace_id"]
        for s in _debug(server, "/debug/traces", n=500).json()["spans"]
    }


class TestTraceparentHelpers:
    def test_roundtrip(self):
        tp = format_traceparent(0xABC123, 0x42)
        assert tp == f"00-{0xABC123:032x}-{0x42:016x}-01"
        ctx = parse_traceparent(tp)
        assert isinstance(ctx, SpanContext)
        assert ctx.trace_id == 0xABC123 and ctx.span_id == 0x42

    def test_mint_parses(self):
        ctx = parse_traceparent(mint_traceparent())
        assert ctx is not None
        assert ctx.trace_id != 0 and ctx.span_id != 0

    @pytest.mark.parametrize(
        "bad",
        [
            "",
            "garbage",
            "00-zz-11-01",
            "00-" + "0" * 32 + "-" + "1" * 16 + "-01",  # zero trace id
            "00-" + "1" * 32 + "-" + "0" * 16 + "-01",  # zero span id
            "00-" + "1" * 31 + "-" + "1" * 16 + "-01",  # short trace id
        ],
    )
    def test_rejects_malformed(self, bad):
        assert parse_traceparent(bad) is None

    def test_current_traceparent_requires_active_span(self):
        assert current_traceparent() is None


class TestTimeLedger:
    def test_marks_attribute_intervals(self):
        led = TimeLedger(t0=100.0)
        led.mark("admission", now=100.010)
        led.mark("queue", now=100.030)
        led.mark("kernel", now=100.031)
        assert led.stages["admission"] == pytest.approx(0.010)
        assert led.stages["queue"] == pytest.approx(0.020)
        assert led.attributed() == pytest.approx(0.031)

    def test_conservation_is_by_construction(self):
        """Stages + the explicit unattributed residual must equal wall
        time exactly — the property the bench smoke gate asserts at 95%
        end to end."""
        led = TimeLedger(t0=0.0)
        now = 0.0
        for stage, dt in [
            ("admission", 0.001),
            ("queue", 0.004),
            ("encode", 0.002),
            ("launch", 0.0005),
            ("kernel", 0.020),
            ("decode", 0.003),
            ("serialize", 0.001),
            ("reply", 0.0002),
        ]:
            now += dt
            led.mark(stage, now=now)
        wall = now + 0.0013  # some untracked tail
        agg = AttributionLedger()
        agg.record(led, wall_s=wall)
        snap = agg.snapshot()
        total = sum(
            info["seconds"] for info in snap["stages"].values()
        )
        # snapshot rounds seconds to 6dp and coverage to 4dp
        assert total == pytest.approx(wall, abs=1e-5)
        assert snap["stages"][UNATTRIBUTED]["seconds"] == pytest.approx(
            0.0013, abs=1e-6
        )
        assert snap["coverage"] == pytest.approx(
            led.attributed() / wall, abs=1e-3
        )
        assert snap["coverage"] > 0.95

    def test_snapshot_orders_canonical_stages_first(self):
        led = TimeLedger(t0=0.0)
        led.mark("kernel", now=0.5)
        agg = AttributionLedger()
        agg.record(led, wall_s=0.5)
        stages = list(agg.snapshot()["stages"])
        known = [s for s in stages if s in ATTRIBUTION_STAGES]
        assert known == [
            s for s in ATTRIBUTION_STAGES if s in set(known)
        ]

    def test_ambient_ledger_contextvar(self):
        assert current_ledger() is None
        ledger_mark("kernel")  # no ambient ledger: must be a no-op
        led = TimeLedger(t0=0.0)
        token = set_current_ledger(led)
        try:
            assert current_ledger() is led
            ledger_mark("admission")
            assert "admission" in led.stages
        finally:
            reset_current_ledger(token)
        assert current_ledger() is None


class TestRestTracePropagation:
    def test_client_traceparent_reaches_spans_flight_and_exemplars(
        self, server
    ):
        from keto_tpu.client import RestClient

        with RestClient(f"http://127.0.0.1:{server.read_port}") as c:
            res = c.check("videos:/cats#view@nobody")
        assert res.traceparent
        tid = _trace_id_of(res.traceparent)
        assert int(tid, 16) != 0

        # the same trace id must appear in server-side spans ...
        assert tid in _span_trace_ids(server)
        # ... in the flight record for this request ...
        recs = _flight_trace_ids(server)
        assert tid in recs
        assert recs[tid][0]["transport"] == "rest"
        # ... with the per-request ledger riding the record
        ledger_ms = recs[tid][0].get("ledger_ms") or {}
        assert "serialize" in ledger_ms and "reply" in ledger_ms
        # ... and in the duration histogram's OpenMetrics exemplar
        exposition = httpx.get(
            f"http://127.0.0.1:{server.read_port}/metrics",
            headers={"Accept": "application/openmetrics-text"},
        ).text
        assert tid in exposition

    def test_explicit_traceparent_is_honored(self, server):
        from keto_tpu.client import RestClient

        tp = mint_traceparent()
        with RestClient(f"http://127.0.0.1:{server.read_port}") as c:
            res = c.check("videos:/cats#view@nobody", traceparent=tp)
        assert res.traceparent == tp
        assert _trace_id_of(tp) in _flight_trace_ids(server)

    def test_batch_check_carries_trace(self, server):
        from keto_tpu.client import RestClient

        tp = mint_traceparent()
        with RestClient(f"http://127.0.0.1:{server.read_port}") as c:
            c.batch_check(
                ["videos:/cats#view@a", "videos:/cats#view@b"],
                traceparent=tp,
            )
        recs = _flight_trace_ids(server)
        assert _trace_id_of(tp) in recs
        assert recs[_trace_id_of(tp)][0]["transport"] == "rest_batch"


class TestGrpcTracePropagation:
    def test_grpc_check_joins_client_trace(self, server):
        from keto_tpu.client import GrpcClient

        with GrpcClient(f"127.0.0.1:{server.read_port}") as g:
            res = g.check("videos:/cats#view@nobody")
        tid = _trace_id_of(res.traceparent)
        assert tid in _span_trace_ids(server)
        recs = _flight_trace_ids(server)
        assert tid in recs
        assert recs[tid][0]["transport"] == "grpc"

    def test_hedged_duplicate_shares_trace_and_is_tagged(self, server):
        """The hedged-duplicate case: one traceparent, two server-side
        requests, the reissue alone tagged hedge — so the operator can
        tell them apart while correlating both to the one client call."""
        from keto_tpu.client import GrpcClient, HedgePolicy, Hedger
        from keto_tpu.faults import FAULTS

        # the primary rides a one-shot 300ms replica stall; the hedge
        # fires at 30ms, dodges it, and wins
        FAULTS.arm_slow("replica.slow", sleep_ms=300, times=1)
        try:
            with GrpcClient(f"127.0.0.1:{server.read_port}") as g:
                with Hedger(HedgePolicy(delay_s=0.03)) as h:
                    out = g.check_hedged("videos:/cats#view@nobody", h)
        finally:
            FAULTS.disarm("replica.slow")
        assert out.hedged is True
        tid = _trace_id_of(out.result.traceparent)

        # both attempts eventually finish server-side; wait for both
        # flight records (the stalled primary lands ~300ms later)
        deadline = time.monotonic() + 5.0
        recs = []
        while time.monotonic() < deadline:
            recs = _flight_trace_ids(server).get(tid, [])
            if len(recs) >= 2:
                break
            time.sleep(0.05)
        assert len(recs) == 2, f"expected 2 flight records, got {recs}"
        hedge_flags = sorted(bool(r.get("hedge")) for r in recs)
        assert hedge_flags == [False, True]
        assert tid in _span_trace_ids(server)


class TestAttributionEndpoint:
    def test_ledger_conservation_under_slowness(self, server):
        """The acceptance property, end to end: with slowness faults
        armed, /debug/attribution must still decompose batch-check wall
        time into named stages summing to >= 95% of measured wall."""
        from keto_tpu.client import GrpcClient, RestClient
        from keto_tpu.faults import FAULTS

        # both slowness seams, as in the bench tail phase: device.slow
        # fires on device query paths, replica.slow on any
        FAULTS.arm_slow("device.slow", sleep_ms=20, times=3)
        FAULTS.arm_slow("replica.slow", sleep_ms=20, times=3)
        try:
            with RestClient(
                f"http://127.0.0.1:{server.read_port}"
            ) as rc:
                rc.batch_check(
                    [f"videos:/cats#view@u{i}" for i in range(32)]
                )
            with GrpcClient(f"127.0.0.1:{server.read_port}") as g:
                for i in range(8):
                    g.check(f"videos:/cats#view@w{i}")
        finally:
            FAULTS.disarm("device.slow")
            FAULTS.disarm("replica.slow")

        payload = _debug(server, "/debug/attribution").json()
        snap = payload["attribution"]
        assert snap["requests"] > 0
        assert snap["coverage"] >= 0.95
        # conservation: stages (incl. the explicit residual) sum to wall
        total = sum(
            info["seconds"] for info in snap["stages"].values()
        )
        # stage seconds are rounded to 6dp each in the snapshot
        assert total == pytest.approx(snap["wall_s"], abs=1e-4)
        # the serving stages the transports mark must be present
        for stage in ("serialize", "reply"):
            assert stage in snap["stages"]
        # the engine built at boot reports its phase split alongside
        phases = payload.get("closure_build_phases")
        if phases:
            assert "total" in phases

    def test_attribution_counter_exposed(self, server):
        body = httpx.get(
            f"http://127.0.0.1:{server.read_port}/metrics"
        ).text
        assert "keto_time_attribution_seconds_total" in body
        assert 'stage="serialize"' in body


class TestSamplingProfiler:
    def test_samples_fold_and_overhead_stays_bounded(self):
        from keto_tpu.telemetry.profiler import SamplingProfiler

        stop = threading.Event()

        def busy():
            while not stop.is_set():
                sum(i * i for i in range(2000))

        worker = threading.Thread(target=busy, name="busy-worker")
        worker.start()
        prof = SamplingProfiler(hz=67.0)
        prof.start()
        try:
            time.sleep(0.6)
        finally:
            prof.stop()
            stop.set()
            worker.join(timeout=5)
        snap = prof.snapshot()
        assert snap["samples"] > 5
        assert snap["self_overhead"] < 0.05  # the acceptance budget
        folds = prof.folded()
        assert any(k.startswith("busy-worker;") for k in folds)
        # folded text is the classic `stack count` line format
        for line in prof.folded_text().splitlines():
            assert re.fullmatch(r".+ \d+", line)
        # tree value equals total folded samples
        assert prof.tree()["value"] == sum(folds.values())

    def test_bounded_fold_table_truncates(self):
        """With max_stacks=1, distinct stacks beyond the first land in
        the [truncated] overflow bucket instead of growing the table."""
        from keto_tpu.telemetry.profiler import SamplingProfiler

        stop = threading.Event()

        def loop_a():
            while not stop.is_set():
                time.sleep(0.01)

        def loop_b():
            while not stop.is_set():
                time.sleep(0.01)

        threads = [
            threading.Thread(target=loop_a, name="fold-a"),
            threading.Thread(target=loop_b, name="fold-b"),
        ]
        for t in threads:
            t.start()
        prof = SamplingProfiler(hz=67.0, max_stacks=1)
        try:
            for _ in range(10):
                prof._sample_once()
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=5)
        folds = prof.folded()
        # one real entry at most, everything else overflowed
        assert len(folds) <= 2
        assert folds.get("[truncated]", 0) > 0
        assert prof.snapshot()["truncated_stacks"] > 0

    def test_pprof_endpoint_on_demand_capture(self, server):
        r = _debug(server, "/debug/pprof", seconds=0.3)
        assert r.status_code == 200
        doc = r.json()
        assert doc["profiler"]["samples"] > 0
        # every sample lands in exactly one stack, so the tree root's
        # subtree total equals the sample count
        assert doc["tree"]["value"] == doc["profiler"]["samples"]
        folded = _debug(server, "/debug/pprof", format="folded")
        assert folded.status_code == 200
        assert folded.text.strip()  # server threads always have frames


class TestFlameTool:
    def _flame(self):
        spec = importlib.util.spec_from_file_location(
            "flame", os.path.join(_REPO, "tools", "flame.py")
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_folded_to_html(self):
        flame = self._flame()
        folds = flame.parse_folded(
            "main;engine:check 42\nmain;api:reply 10\nbad line\n"
        )
        assert folds == {
            ("main", "engine:check"): 42,
            ("main", "api:reply"): 10,
        }
        tree = flame.build_tree(folds)
        assert tree["value"] == 52
        html = flame.render_html(tree)
        assert "<svg" in html and "engine:check" in html
        svg = flame.render_svg(tree)
        assert svg.startswith("<svg") and svg.endswith("</svg>")

    def test_profiler_folded_feeds_flame(self, server):
        flame = self._flame()
        text = _debug(server, "/debug/pprof", format="folded").text
        folds = flame.parse_folded(text)
        assert folds
        html = flame.render_html(flame.build_tree(folds))
        assert "<svg" in html
