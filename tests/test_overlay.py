"""Write-overlay freshness: exact serving-time deltas over the resident
closure (engine/overlay.py; VERDICT r3 #3 — bounded staleness under deletes
without the full-rebuild cliff).

The property under test everywhere: with bounded freshness, after ANY
sequence of leaf writes/deletes the engine answers exactly like a fresh
host oracle at the live store version, WITHOUT having rebuilt the closure;
interior-edge inserts absorb into D in place; interior-edge deletes absorb
via the bounded exact re-close of affected D rows (r5: VERDICT r4 weak #3);
only cap/budget overflow falls back to the rebuild path — and remains
correct there.
"""

import numpy as np
import pytest

from keto_tpu.engine import CheckEngine
from keto_tpu.engine.closure import ClosureCheckEngine, _ClosureArtifacts
from keto_tpu.graph import SnapshotManager
from keto_tpu.relationtuple import RelationTuple
from keto_tpu.store import InMemoryTupleStore

from test_device_engines import random_store


def t(s: str) -> RelationTuple:
    return RelationTuple.from_string(s)


def _requests(rng, n_objects, n_users, k):
    reqs = []
    for _ in range(k):
        obj = f"o{rng.integers(n_objects)}"
        rel = f"r{rng.integers(3)}"
        if rng.random() < 0.3:
            sub = f"n:o{rng.integers(n_objects)}#r{rng.integers(3)}"
        else:
            sub = f"u{rng.integers(n_users)}"
        reqs.append(t(f"n:{obj}#{rel}@({sub})"))
    return reqs


def make_engine(store, **kw):
    kw.setdefault("max_depth", 5)
    kw.setdefault("freshness", "bounded")
    kw.setdefault("rebuild_debounce_s", 0.0)
    eng = ClosureCheckEngine(SnapshotManager(store), **kw)
    return eng


def assert_live_parity(eng, store, reqs, depths=(0,)):
    oracle = CheckEngine(store, max_depth=eng.global_max_depth)
    for d in depths:
        got = eng.batch_check(reqs, max_depth=d)
        want = oracle.batch_check(reqs, max_depth=d)
        assert got == want


class TestLeafWrites:
    @pytest.mark.parametrize("seed", range(4))
    def test_random_leaf_mutations_stay_exact_without_rebuild(self, seed):
        rng = np.random.default_rng(seed)
        store = random_store(rng, n_objects=12, n_users=9, n_edges=110)
        eng = make_engine(store)
        reqs = _requests(rng, 12, 9, 96)
        eng.batch_check(reqs)  # build the base closure
        builds0 = eng.n_full_builds + eng.n_incremental_builds
        # interleave leaf writes and deletes with checks
        all_tuples = store.all_tuples()
        for step in range(6):
            victims = [
                all_tuples[i]
                for i in rng.integers(len(all_tuples), size=3)
            ]
            # only delete leaf edges (subject-id dst, or src not interior):
            # pick id-subject tuples — always leaf
            victims = [
                v for v in victims if not hasattr(v.subject, "relation")
            ]
            if victims:
                store.delete_relation_tuples(*victims)
            store.write_relation_tuples(
                t(f"n:o{rng.integers(12)}#r{rng.integers(3)}"
                  f"@u{rng.integers(9)}"),
                t(f"n:o{rng.integers(12)}#r{rng.integers(3)}"
                  f"@newuser{step}"),
            )
            assert_live_parity(eng, store, reqs, depths=(0, 2))
            # served at the LIVE version, via overlay — not a rebuild
            assert eng.served_version() == store.version
        assert eng.n_full_builds + eng.n_incremental_builds == builds0

    def test_delete_then_reinsert_roundtrip(self):
        store = InMemoryTupleStore()
        store.write_relation_tuples(
            t("n:doc#view@(n:g#m)"), t("n:g#m@alice")
        )
        eng = make_engine(store)
        q = [t("n:doc#view@alice"), t("n:g#m@alice")]
        assert eng.batch_check(q) == [True, True]
        store.delete_relation_tuples(t("n:g#m@alice"))
        assert eng.batch_check(q) == [False, False]
        assert eng.served_version() == store.version
        store.write_relation_tuples(t("n:g#m@alice"))
        assert eng.batch_check(q) == [True, True]
        assert eng.n_full_builds == 1  # the initial build only

    def test_new_user_and_new_object_after_snapshot(self):
        """Nodes interned after the base snapshot (beyond padded width)
        must resolve through the overlay, not clamp to dummy-deny."""
        store = InMemoryTupleStore()
        store.write_relation_tuples(t("n:doc#view@(n:g#m)"))
        eng = make_engine(store)
        assert eng.subject_is_allowed(t("n:doc#view@zoe")) is False
        store.write_relation_tuples(t("n:g#m@zoe"))
        assert eng.subject_is_allowed(t("n:doc#view@zoe")) is True
        # brand-new object too
        store.write_relation_tuples(t("n:newdoc#view@zoe"))
        assert eng.subject_is_allowed(t("n:newdoc#view@zoe")) is True
        assert eng.n_full_builds == 1

    def test_direct_edge_delete_with_surviving_indirect_path(self):
        store = InMemoryTupleStore()
        store.write_relation_tuples(
            t("n:doc#view@alice"),  # direct
            t("n:doc#view@(n:g#m)"),
            t("n:g#m@alice"),  # indirect, depth 3... actually 2
        )
        eng = make_engine(store)
        assert eng.subject_is_allowed(t("n:doc#view@alice")) is True
        store.delete_relation_tuples(t("n:doc#view@alice"))
        # the direct edge is gone but the group path survives
        assert eng.subject_is_allowed(t("n:doc#view@alice")) is True
        # at depth 1 only the (deleted) direct edge would have counted
        assert eng.subject_is_allowed(t("n:doc#view@alice"), 1) is False
        store.delete_relation_tuples(t("n:g#m@alice"))
        assert eng.subject_is_allowed(t("n:doc#view@alice")) is False
        assert eng.n_full_builds == 1


class TestInteriorWrites:
    def test_interior_edge_insert_patches_closure_in_place(self):
        store = InMemoryTupleStore()
        store.write_relation_tuples(
            t("n:doc#view@(n:g1#m)"),
            t("n:g2#m@alice"),
            t("n:g2#m@(n:g3#m)"),  # make g2, g3 interior
            t("n:g3#m@bob"),
        )
        eng = make_engine(store)
        assert eng.subject_is_allowed(t("n:doc#view@alice")) is False
        # new interior edge g1 -> g2 (both ends interior-capable)
        store.write_relation_tuples(t("n:g1#m@(n:g2#m)"))
        assert eng.subject_is_allowed(t("n:doc#view@alice")) is True
        # path doc -> g1 -> g2 -> g3 -> bob needs depth 4
        assert eng.subject_is_allowed(t("n:doc#view@bob"), 4) is True
        assert eng.subject_is_allowed(t("n:doc#view@bob"), 3) is False
        assert eng.n_full_builds == 1

    def test_new_interior_node_grows_into_padding(self):
        store = InMemoryTupleStore()
        store.write_relation_tuples(t("n:g0#m@(n:g1#m)"), t("n:g1#m@u0"))
        eng = make_engine(store)
        eng.batch_check([t("n:g0#m@u0")])
        # a chain of brand-new set nodes: each becomes interior via overlay
        store.write_relation_tuples(t("n:g1#m@(n:h1#x)"))
        store.write_relation_tuples(t("n:h1#x@(n:h2#x)"))
        store.write_relation_tuples(t("n:h2#x@carol"))
        oracle = CheckEngine(store, max_depth=5)
        reqs = [
            t("n:g0#m@carol"),
            t("n:g0#m@(n:h2#x)"),
            t("n:h1#x@carol"),
        ]
        assert eng.batch_check(reqs) == oracle.batch_check(reqs)
        assert eng.served_version() == store.version
        assert eng.n_full_builds == 1

    def test_interior_delete_absorbed_without_rebuild(self):
        store = InMemoryTupleStore()
        store.write_relation_tuples(
            t("n:doc#view@(n:g1#m)"),
            t("n:g1#m@(n:g2#m)"),
            t("n:g2#m@alice"),
        )
        eng = make_engine(store)
        assert eng.subject_is_allowed(t("n:doc#view@alice")) is True
        builds0 = eng.n_full_builds + eng.n_incremental_builds
        # deleting the interior g1->g2 edge: bounded exact re-close of
        # the affected D rows — NO rebuild (r5; used to be the one
        # full-rebuild cliff left)
        store.delete_relation_tuples(t("n:g1#m@(n:g2#m)"))
        assert eng.subject_is_allowed(t("n:doc#view@alice")) is False
        assert eng.n_full_builds + eng.n_incremental_builds == builds0
        assert eng.served_version() == store.version

    def test_interior_delete_keeps_surviving_longer_path(self):
        """Deleting one interior edge must re-lengthen, not sever: a
        surviving longer path through another group must still answer
        True (with the correct new depth requirement)."""
        store = InMemoryTupleStore()
        store.write_relation_tuples(
            t("n:doc#view@(n:g1#m)"),
            t("n:g1#m@(n:g3#m)"),      # short path: doc -> g1 -> g3
            t("n:g1#m@(n:g2#m)"),      # long path: doc -> g1 -> g2 -> g3
            t("n:g2#m@(n:g3#m)"),
            t("n:g3#m@alice"),
        )
        eng = make_engine(store)
        assert eng.subject_is_allowed(t("n:doc#view@alice"), 3) is True
        builds0 = eng.n_full_builds + eng.n_incremental_builds
        store.delete_relation_tuples(t("n:g1#m@(n:g3#m)"))
        # still reachable via g2, one hop longer
        assert eng.subject_is_allowed(t("n:doc#view@alice"), 4) is True
        assert eng.subject_is_allowed(t("n:doc#view@alice"), 3) is False
        assert eng.n_full_builds + eng.n_incremental_builds == builds0
        assert_live_parity(eng, store, [t("n:doc#view@alice")], depths=(0, 3, 4))

    def test_interior_delete_of_overlay_inserted_edge(self):
        """Insert an interior edge through the overlay, then delete it
        again: the re-close must consult the CURRENT adjacency (base +
        overlay-inserted - deleted), not the base CSR alone."""
        store = InMemoryTupleStore()
        store.write_relation_tuples(
            t("n:doc#view@(n:g1#m)"),
            t("n:g1#m@x"),
            t("n:g2#m@alice"),
            t("n:top#m@(n:g2#m)"),  # make g2 interior in the base
        )
        eng = make_engine(store)
        eng.batch_check([t("n:doc#view@alice")])
        store.write_relation_tuples(t("n:g1#m@(n:g2#m)"))  # overlay insert
        assert eng.subject_is_allowed(t("n:doc#view@alice")) is True
        store.delete_relation_tuples(t("n:g1#m@(n:g2#m)"))  # overlay delete
        assert eng.subject_is_allowed(t("n:doc#view@alice")) is False
        # and re-insert brings it back
        store.write_relation_tuples(t("n:g1#m@(n:g2#m)"))
        assert eng.subject_is_allowed(t("n:doc#view@alice")) is True
        assert eng.served_version() == store.version

    def test_interior_delete_budget_breaks_to_rebuild(self):
        """A delete whose candidate row set exceeds max_delete_rows must
        break the overlay (rebuild path) and still answer correctly."""
        from keto_tpu.engine.overlay import WriteOverlay

        store = InMemoryTupleStore()
        store.write_relation_tuples(
            t("n:doc#view@(n:g1#m)"),
            t("n:g1#m@(n:g2#m)"),
            t("n:g2#m@alice"),
        )
        eng = make_engine(store)
        eng.batch_check([t("n:doc#view@alice")])
        ov = eng._overlay
        assert isinstance(ov, WriteOverlay)
        ov.max_delete_rows = 0  # force the budget break
        store.delete_relation_tuples(t("n:g1#m@(n:g2#m)"))
        eng.batch_check([t("n:doc#view@alice")])  # drains -> breaks
        assert ov.broken and "interior delete" in ov.broken_reason
        # bounded freshness: stale until the rebuild lands, then exact
        eng.wait_for_version(store.version, timeout_s=30)
        assert eng.subject_is_allowed(t("n:doc#view@alice")) is False


class TestPromotionReclassification:
    def test_chain_built_tuple_by_tuple_from_empty_store(self):
        """The cat-videos regression: an engine whose base snapshot is
        EMPTY sees every edge via overlay; nodes promoted to interior must
        reclassify their earlier OVERLAY out-edges (id successors into L,
        set successors into D), not only base edges."""
        store = InMemoryTupleStore()
        eng = make_engine(store)
        eng.batch_check([t("videos:/cats#owner@nobody")])  # empty base
        for s in [
            "videos:/cats#owner@(cat lady)",
            "videos:/cats/1.mp4#owner@(videos:/cats#owner)",
            "videos:/cats/1.mp4#view@(videos:/cats/1.mp4#owner)",
        ]:
            store.write_relation_tuples(t(s))
        oracle = CheckEngine(store, max_depth=5)
        reqs = [
            t("videos:/cats#owner@(cat lady)"),
            t("videos:/cats/1.mp4#owner@(cat lady)"),
            t("videos:/cats/1.mp4#view@(cat lady)"),  # two indirections
            t("videos:/cats/1.mp4#view@(dog guy)"),
        ]
        assert eng.batch_check(reqs) == oracle.batch_check(reqs) == [
            True, True, True, False,
        ]
        assert eng.n_full_builds == 1

    def test_transact_insert_and_delete_same_set_tuple(self):
        """A transact inserting AND deleting the same set-subject tuple
        nets to absent; the overlay must apply inserts first (store order)
        so the delete sees the promotion's index — a delete-first pass
        left a phantom F0 entry granting a permission that doesn't exist."""
        store = InMemoryTupleStore()
        store.write_relation_tuples(t("n:g#m@alice"))  # g#m exterior
        eng = make_engine(store)
        eng.batch_check([t("n:g#m@alice")])
        store.transact_relation_tuples(
            insert=[t("n:doc#view@(n:g#m)")],
            delete=[t("n:doc#view@(n:g#m)")],
        )
        oracle = CheckEngine(store, max_depth=5)
        reqs = [t("n:doc#view@alice"), t("n:g#m@alice")]
        assert eng.batch_check(reqs) == oracle.batch_check(reqs) == [
            False, True,
        ]

    def test_promotion_skips_overlay_deleted_base_edges(self):
        """A base out-edge deleted via overlay must NOT be resurrected
        when its source node is later promoted to interior."""
        store = InMemoryTupleStore()
        store.write_relation_tuples(t("n:g#m@alice"))  # base: g -> alice
        eng = make_engine(store)
        eng.batch_check([t("n:g#m@alice")])
        store.delete_relation_tuples(t("n:g#m@alice"))
        # now promote g by giving it an in-edge
        store.write_relation_tuples(t("n:doc#view@(n:g#m)"))
        oracle = CheckEngine(store, max_depth=5)
        reqs = [t("n:doc#view@alice"), t("n:g#m@alice")]
        assert eng.batch_check(reqs) == oracle.batch_check(reqs) == [
            False, False,
        ]
        assert eng.n_full_builds == 1


class TestOverlayLifecycle:
    def test_wait_for_version_satisfied_by_overlay(self):
        store = InMemoryTupleStore()
        store.write_relation_tuples(t("n:doc#view@(n:g#m)"))
        eng = make_engine(store)
        eng.batch_check([t("n:doc#view@alice")])
        store.write_relation_tuples(t("n:g#m@alice"))
        # the overlay covers the write: no 503, no rebuild wait
        eng.wait_for_version(store.version, timeout_s=0.5)
        assert eng.subject_is_allowed(t("n:doc#view@alice")) is True

    def test_cap_overflow_breaks_overlay_then_rebuild_recovers(self):
        rng = np.random.default_rng(0)
        store = random_store(rng, n_objects=8, n_users=6, n_edges=60)
        eng = make_engine(store)
        reqs = _requests(rng, 8, 6, 64)
        eng.batch_check(reqs)
        eng._overlay.max_events = 4  # force the cap
        for i in range(6):
            store.write_relation_tuples(t(f"n:o1#r0@x{i}"))
        # overlay broke; bounded freshness serves stale then catches up
        assert_live_parity_eventually(eng, store, reqs)

    def test_mixed_random_mutations_vs_oracle(self):
        """The big one: arbitrary interleaved writes/deletes (incl.
        interior) with parity asserted against a fresh oracle after every
        step, across freshness policies."""
        for policy in ("bounded", "strong"):
            rng = np.random.default_rng(42)
            store = random_store(rng, n_objects=10, n_users=8, n_edges=90)
            eng = make_engine(store, freshness=policy)
            reqs = _requests(rng, 10, 8, 80)
            eng.batch_check(reqs)
            for step in range(8):
                roll = rng.random()
                if roll < 0.4:
                    all_t = store.all_tuples()
                    victims = [
                        all_t[i]
                        for i in rng.integers(len(all_t), size=2)
                    ]
                    store.delete_relation_tuples(*victims)
                elif roll < 0.8:
                    store.write_relation_tuples(
                        *_requests(rng, 10, 8, 3)
                    )
                else:
                    store.write_relation_tuples(
                        t(f"n:o{rng.integers(10)}#r0"
                          f"@(n:o{rng.integers(10)}#r1)")
                    )
                assert_live_parity_eventually(eng, store, reqs)


def assert_live_parity_eventually(eng, store, reqs, timeout_s=10.0):
    """Parity at the live version, allowing the bounded-freshness rebuild
    to land first when the overlay could not absorb the writes."""
    import time

    oracle = CheckEngine(store, max_depth=eng.global_max_depth)
    want = oracle.batch_check(reqs)
    deadline = time.monotonic() + timeout_s
    while True:
        try:
            eng.wait_for_version(store.version, timeout_s=2.0)
        except Exception:
            pass
        got = eng.batch_check(reqs)
        if got == want and eng.served_version() == store.version:
            return
        if time.monotonic() > deadline:
            assert got == want, "answers never converged to the oracle"
            assert eng.served_version() == store.version
            return
        time.sleep(0.05)


class TestInteriorChurn:
    """Randomized interior-edge churn: interleaved inserts AND deletes of
    group->group edges must stay exact vs the live-store oracle with ZERO
    closure rebuilds — the full absorption property (r5: re-close +
    relaxation + promotion all composing)."""

    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_random_interior_churn_stays_exact_without_rebuild(self, seed):
        rng = np.random.default_rng(seed)
        n_groups = 12
        store = InMemoryTupleStore()
        # base: a layer of docs granting to groups, groups holding users,
        # and some initial nesting so the interior is non-trivial
        base = []
        for g in range(n_groups):
            base.append(t(f"n:g{g}#m@u{g % 5}"))
            base.append(t(f"n:doc{g % 4}#view@(n:g{g}#m)"))
        for _ in range(8):
            a, b = rng.integers(n_groups, size=2)
            base.append(t(f"n:g{a}#m@(n:g{b}#m)"))
        store.write_relation_tuples(*base)
        eng = make_engine(store)
        reqs = [
            t(f"n:doc{d}#view@u{u}") for d in range(4) for u in range(5)
        ] + [
            t(f"n:g{a}#m@u{u}")
            for a in range(0, n_groups, 3)
            for u in range(5)
        ]
        assert_live_parity(eng, store, reqs, depths=(0, 2, 3))
        builds0 = eng.n_full_builds + eng.n_incremental_builds

        for step in range(60):
            a, b = (int(x) for x in rng.integers(n_groups, size=2))
            edge = t(f"n:g{a}#m@(n:g{b}#m)")
            if rng.random() < 0.5:
                store.write_relation_tuples(edge)
            else:
                store.delete_relation_tuples(edge)
            if step % 5 == 0:
                assert_live_parity(eng, store, reqs, depths=(0, 3))
        assert_live_parity(eng, store, reqs, depths=(0, 2, 3, 5))
        assert eng.n_full_builds + eng.n_incremental_builds == builds0, (
            "interior churn must absorb without rebuilds"
        )
        assert eng.served_version() == store.version
