"""Device engines vs host oracle: the batched frontier kernels must agree
bit-for-bit with the host BFS check engine and the store-backed expand engine
on every graph — including cycles, unknown subjects, and depth clamping
(the scenario matrix of reference internal/check/engine_test.go:45-581,
re-run against the device path)."""

import numpy as np
import pytest

from keto_tpu.engine import CheckEngine, ExpandEngine
from keto_tpu.engine.device import DeviceCheckEngine, SnapshotExpandEngine
from keto_tpu.graph import SnapshotManager
from keto_tpu.relationtuple import RelationTuple, SubjectID, SubjectSet
from keto_tpu.store import InMemoryTupleStore


def t(s: str) -> RelationTuple:
    return RelationTuple.from_string(s)


@pytest.fixture
def store():
    # no namespace validation: these tests exercise the engines
    return InMemoryTupleStore()


def make_engines(store, mode, max_depth=5):
    mgr = SnapshotManager(store)
    return (
        CheckEngine(store, max_depth=max_depth),
        DeviceCheckEngine(mgr, max_depth=max_depth, mode=mode),
    )


@pytest.fixture(params=["dense", "scatter"])
def mode(request):
    return request.param


class TestDeviceCheckScenarios:
    """Reference check scenarios (engine_test.go) against the device path."""

    def test_direct_inclusion(self, store, mode):
        store.write_relation_tuples(t("n:obj#access@alice"))
        _, dev = make_engines(store, mode)
        assert dev.subject_is_allowed(t("n:obj#access@alice"))
        assert not dev.subject_is_allowed(t("n:obj#access@bob"))

    def test_indirect_inclusion_two_levels(self, store, mode):
        store.write_relation_tuples(
            t("n:obj#access@(n:org#member)"),
            t("n:org#member@(n:team#member)"),
            t("n:team#member@alice"),
        )
        _, dev = make_engines(store, mode)
        assert dev.subject_is_allowed(t("n:obj#access@alice"))
        assert dev.subject_is_allowed(t("n:obj#access@(n:team#member)"))
        assert not dev.subject_is_allowed(t("n:obj#access@mallory"))

    def test_wrong_object_or_relation(self, store, mode):
        store.write_relation_tuples(t("n:obj#access@alice"))
        _, dev = make_engines(store, mode)
        assert not dev.subject_is_allowed(t("n:other#access@alice"))
        assert not dev.subject_is_allowed(t("n:obj#write@alice"))
        assert not dev.subject_is_allowed(t("other:obj#access@alice"))

    def test_circular_tuples_terminate(self, store, mode):
        store.write_relation_tuples(
            t("n:a#r@(n:b#r)"),
            t("n:b#r@(n:a#r)"),
        )
        _, dev = make_engines(store, mode)
        assert not dev.subject_is_allowed(t("n:a#r@alice"))
        # the sets themselves are mutually reachable
        assert dev.subject_is_allowed(t("n:a#r@(n:a#r)"))

    def test_depth_budget(self, store, mode):
        # chain of 4 indirections: obj#r -> s1 -> s2 -> s3 -> alice
        store.write_relation_tuples(
            t("n:obj#r@(n:s1#m)"),
            t("n:s1#m@(n:s2#m)"),
            t("n:s2#m@(n:s3#m)"),
            t("n:s3#m@alice"),
        )
        _, dev = make_engines(store, mode, max_depth=10)
        req = t("n:obj#r@alice")
        assert not dev.subject_is_allowed(req, max_depth=3)
        assert dev.subject_is_allowed(req, max_depth=4)
        # depth <= 0 and depth > global clamp to global
        assert dev.subject_is_allowed(req, max_depth=0)
        assert dev.subject_is_allowed(req, max_depth=99)

    def test_global_max_depth_precedence(self, store, mode):
        store.write_relation_tuples(
            t("n:obj#r@(n:s1#m)"),
            t("n:s1#m@(n:s2#m)"),
            t("n:s2#m@alice"),
        )
        _, dev = make_engines(store, mode, max_depth=2)
        # global cap 2 < required 3: denied even when request asks for more
        assert not dev.subject_is_allowed(t("n:obj#r@alice"), max_depth=50)

    def test_subject_set_exact_match_semantics(self, store, mode):
        # requesting the queried set itself is not auto-allowed
        store.write_relation_tuples(t("n:obj#r@alice"))
        _, dev = make_engines(store, mode)
        assert not dev.subject_is_allowed(t("n:obj#r@(n:obj#r)"))

    def test_unknown_everything(self, store, mode):
        _, dev = make_engines(store, mode)
        assert not dev.subject_is_allowed(t("no:thing#here@nobody"))

    def test_write_visibility(self, store, mode):
        _, dev = make_engines(store, mode)
        req = t("n:obj#r@alice")
        assert not dev.subject_is_allowed(req)
        store.write_relation_tuples(req)
        assert dev.subject_is_allowed(req)
        store.delete_relation_tuples(req)
        assert not dev.subject_is_allowed(req)

    def test_batch_mixed_depths(self, store, mode):
        store.write_relation_tuples(
            t("n:obj#r@(n:s1#m)"),
            t("n:s1#m@alice"),
            t("n:obj#r@bob"),
        )
        _, dev = make_engines(store, mode)
        reqs = [t("n:obj#r@alice"), t("n:obj#r@bob"), t("n:obj#r@eve")]
        assert dev.batch_check(reqs, depths=[1, 1, 5]) == [False, True, False]
        assert dev.batch_check(reqs, depths=[2, 1, 5]) == [True, True, False]


def random_store(rng, n_objects, n_users, n_edges, n_rel=3):
    """Random tuple graph with a healthy share of subject-set indirections."""
    store = InMemoryTupleStore()
    tuples = set()
    for _ in range(n_edges):
        obj = f"o{rng.integers(n_objects)}"
        rel = f"r{rng.integers(n_rel)}"
        if rng.random() < 0.45:
            sub = f"n:o{rng.integers(n_objects)}#r{rng.integers(n_rel)}"
        else:
            sub = f"u{rng.integers(n_users)}"
        tuples.add(f"n:{obj}#{rel}@({sub})")
    store.write_relation_tuples(*(t(s) for s in tuples))
    return store


class TestDeviceMatchesOracle:
    @pytest.mark.parametrize("seed", range(4))
    def test_random_graphs_check(self, mode, seed):
        rng = np.random.default_rng(seed)
        store = random_store(rng, n_objects=15, n_users=10, n_edges=120)
        for depth in (1, 2, 3, 5, 8):
            host, dev = make_engines(store, mode, max_depth=depth)
            reqs = []
            for _ in range(64):
                obj = f"o{rng.integers(15)}"
                rel = f"r{rng.integers(3)}"
                if rng.random() < 0.3:
                    sub = f"n:o{rng.integers(15)}#r{rng.integers(3)}"
                else:
                    sub = f"u{rng.integers(10)}"
                reqs.append(t(f"n:{obj}#{rel}@({sub})"))
            expect = [host.subject_is_allowed(r) for r in reqs]
            got = dev.batch_check(reqs)
            assert got == expect, f"seed={seed} depth={depth}"

    @pytest.mark.parametrize("seed", range(3))
    def test_random_graphs_expand(self, seed):
        rng = np.random.default_rng(seed + 100)
        store = random_store(rng, n_objects=10, n_users=8, n_edges=60)
        mgr = SnapshotManager(store)
        host = ExpandEngine(store, max_depth=7)
        dev = SnapshotExpandEngine(mgr, max_depth=7)
        for depth in (1, 2, 4, 7):
            for o in range(10):
                for r in range(3):
                    subject = SubjectSet(
                        namespace="n", object=f"o{o}", relation=f"r{r}"
                    )
                    ht = host.build_tree(subject, max_depth=depth)
                    dt = dev.build_tree(subject, max_depth=depth)
                    hd = None if ht is None else ht.to_dict()
                    dd = None if dt is None else dt.to_dict()
                    assert hd == dd, f"seed={seed} depth={depth} {subject}"


class TestDistances:
    def test_bfs_levels(self, store):
        store.write_relation_tuples(
            t("n:obj#r@(n:s1#m)"),
            t("n:s1#m@(n:s2#m)"),
            t("n:s2#m@alice"),
        )
        mgr = SnapshotManager(store)
        dev = DeviceCheckEngine(mgr, max_depth=5, mode="dense")
        snap = mgr.snapshot()
        dist = dev.distances(
            [SubjectSet(namespace="n", object="obj", relation="r")]
        )[0]
        assert dist[snap.node_for_set("n", "obj", "r")] == 0
        assert dist[snap.node_for_set("n", "s1", "m")] == 1
        assert dist[snap.node_for_set("n", "s2", "m")] == 2
        assert dist[snap.node_for_subject(SubjectID(id="alice"))] == 3
