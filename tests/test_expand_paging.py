"""Paged Expand: worklist traversal, page stitching, and token hygiene.

The contract (keto_tpu/engine/expand.py + engine/device.py): the explicit
work-stack traversal never hits Python's recursion limit, paged expansion
stitched with ``apply_expand_patches`` is byte-identical to the unpaged
tree for every page size, and continuation tokens fail closed — garbage,
cross-engine, or stale-version tokens all raise ``ErrMalformedPageToken``.
"""

import sys

import numpy as np
import pytest

from keto_tpu.engine.device import SnapshotExpandEngine
from keto_tpu.engine.expand import (
    ExpandEngine,
    decode_expand_page_token,
    encode_expand_page_token,
)
from keto_tpu.engine.tree import NodeType, Tree, apply_expand_patches
from keto_tpu.graph import SnapshotManager
from keto_tpu.namespace import MemoryNamespaceManager
from keto_tpu.relationtuple import RelationTuple, SubjectID, SubjectSet
from keto_tpu.store import InMemoryTupleStore
from keto_tpu.utils.errors import ErrMalformedInput, ErrMalformedPageToken

from test_device_engines import random_store


def t(s: str) -> RelationTuple:
    return RelationTuple.from_string(s)


def make_env(*namespaces):
    nsmgr = MemoryNamespaceManager()
    for n in namespaces:
        nsmgr.add(n)
    store = InMemoryTupleStore(namespace_manager=nsmgr)
    return store, ExpandEngine(store)


def _engines(store, max_depth=None):
    """(name, engine) pairs — the host store-walking engine and the
    snapshot CSR engine share the paging contract."""
    kw = {} if max_depth is None else {"max_depth": max_depth}
    return [
        ("host", ExpandEngine(store, **kw)),
        ("snap", SnapshotExpandEngine(SnapshotManager(store), **kw)),
    ]


def _drain(engine, subject, max_depth=0, page_size=3, max_pages=10_000):
    """Walk every page, stitch, and return (tree, n_pages)."""
    page = engine.build_tree_page(
        subject, max_depth=max_depth, page_size=page_size
    )
    tree = page.tree
    pages = 1
    while page.next_page_token:
        assert pages < max_pages, "paged expand did not terminate"
        page = engine.build_tree_page(
            subject,
            max_depth=max_depth,
            page_size=page_size,
            page_token=page.next_page_token,
        )
        tree = apply_expand_patches(tree, page.patches)
        pages += 1
    return tree, pages


class TestWorklist:
    def test_self_referential_set_terminates(self):
        # a set that contains itself: visited-set suppression degrades the
        # recursive occurrence to a Leaf, no infinite loop / recursion
        store, e = make_env("n")
        store.write_relation_tuples(
            t("n:a#r@(n:a#r)"), t("n:a#r@(u1)")
        )
        for name, eng in _engines(store):
            tree = eng.build_tree(SubjectSet("n", "a", "r"), 100)
            assert tree.type == NodeType.UNION, name
            subjects = {str(c.subject) for c in tree.children}
            assert subjects == {"n:a#r", "u1"}, name
            assert all(c.type == NodeType.LEAF for c in tree.children), name

    def test_chain_beyond_recursion_limit(self):
        # a subject-set chain much deeper than sys.getrecursionlimit():
        # the old recursive engine died with RecursionError here
        depth = sys.getrecursionlimit() + 500
        store, _ = make_env("n")
        store.write_relation_tuples(
            *[t(f"n:c{i}#r@(n:c{i + 1}#r)") for i in range(depth)],
            t(f"n:c{depth}#r@(bottom)"),
        )
        for name, eng in _engines(store, max_depth=depth + 5):
            tree = eng.build_tree(SubjectSet("n", "c0", "r"), depth + 5)
            node, levels = tree, 0
            while node.type == NodeType.UNION:
                (node,) = node.children
                levels += 1
            assert node.subject == SubjectID("bottom"), name
            assert levels == depth + 1, name

    def test_deep_chain_pages_and_stitches(self):
        depth = sys.getrecursionlimit() + 200
        store, _ = make_env("n")
        store.write_relation_tuples(
            *[t(f"n:c{i}#r@(n:c{i + 1}#r)") for i in range(depth)],
            t(f"n:c{depth}#r@(bottom)"),
        )
        for name, eng in _engines(store, max_depth=depth + 5):
            want = eng.build_tree(SubjectSet("n", "c0", "r"), depth + 5)
            got, pages = _drain(
                eng,
                SubjectSet("n", "c0", "r"),
                max_depth=depth + 5,
                page_size=64,
            )
            # Tree.__eq__ recurses — compare the unary chain iteratively
            a, b = got, want
            while True:
                assert (a.type, a.subject) == (b.type, b.subject), name
                assert len(a.children) == len(b.children), name
                if not a.children:
                    break
                (a,), (b,) = a.children, b.children
            assert pages > 1, name


class TestPagingParity:
    @pytest.mark.parametrize("seed", range(8))
    @pytest.mark.parametrize("page_size", [1, 2, 3, 7, 1000])
    def test_stitched_equals_unpaged(self, seed, page_size):
        rng = np.random.default_rng(seed)
        store = random_store(rng, n_objects=12, n_users=8, n_edges=90)
        for name, eng in _engines(store):
            for depth in (2, 4, 7):
                for obj in ("o0", "o3", "o7"):
                    root = SubjectSet("n", obj, "r0")
                    want = eng.build_tree(root, depth)
                    got, _ = _drain(
                        eng, root, max_depth=depth, page_size=page_size
                    )
                    if want is None:
                        assert got is None, (name, obj, depth)
                    else:
                        assert got == want, (name, obj, depth)

    def test_first_page_has_placeholders_then_patched(self):
        store, _ = make_env("n")
        store.write_relation_tuples(
            t("n:root#r@(n:a#m)"),
            t("n:root#r@(n:b#m)"),
            t("n:a#m@(u1)"),
            t("n:a#m@(u2)"),
            t("n:b#m@(u3)"),
        )
        for name, eng in _engines(store):
            root = SubjectSet("n", "root", "r")
            page = eng.build_tree_page(root, max_depth=5, page_size=1)
            # budget of 1: root entered, both set children deferred as
            # placeholder Leaves
            assert page.next_page_token, name
            assert page.tree.type == NodeType.UNION, name
            assert all(
                c.type == NodeType.LEAF for c in page.tree.children
            ), name
            # later pages arrive as path-addressed subtree patches
            tree = page.tree
            while page.next_page_token:
                page = eng.build_tree_page(
                    root,
                    max_depth=5,
                    page_size=1,
                    page_token=page.next_page_token,
                )
                assert page.tree is None, name
                tree = apply_expand_patches(tree, page.patches)
            assert tree == eng.build_tree(root, 5), name

    def test_subject_id_is_single_page(self):
        store, _ = make_env("n")
        for name, eng in _engines(store):
            page = eng.build_tree_page(SubjectID("u1"), page_size=1)
            assert page.next_page_token == "", name
            assert page.tree == Tree(
                type=NodeType.LEAF, subject=SubjectID("u1")
            ), name

    def test_page_dict_shape(self):
        store, _ = make_env("n")
        store.write_relation_tuples(
            t("n:root#r@(n:a#m)"), t("n:a#m@(u1)")
        )
        _, eng = _engines(store)[0]
        root = SubjectSet("n", "root", "r")
        p1 = eng.build_tree_page(root, max_depth=5, page_size=1)
        d1 = p1.to_dict()
        assert "tree" in d1 and "patches" not in d1
        assert d1["next_page_token"] == p1.next_page_token
        p2 = eng.build_tree_page(
            root, max_depth=5, page_size=100, page_token=p1.next_page_token
        )
        d2 = p2.to_dict()
        assert "patches" in d2 and "tree" not in d2
        assert "next_page_token" not in d2
        # patches round-trip through their wire form
        stitched = apply_expand_patches(
            Tree.from_dict(d1["tree"]),
            [(p["path"], p["tree"]) for p in d2["patches"]],
        )
        assert stitched == eng.build_tree(root, 5)


class TestTokens:
    def _token_env(self):
        store, _ = make_env("n")
        store.write_relation_tuples(
            t("n:root#r@(n:a#m)"),
            t("n:a#m@(u1)"),
            t("n:root#r@(n:b#m)"),
            t("n:b#m@(u2)"),
        )
        return store

    def test_garbage_token_rejected(self):
        store = self._token_env()
        for name, eng in _engines(store):
            for bad in ("garbage", "aGVsbG8=", "", "!!!!"):
                with pytest.raises(ErrMalformedPageToken):
                    eng.build_tree_page(
                        SubjectSet("n", "root", "r"),
                        max_depth=5,
                        page_size=1,
                        page_token=bad or "x",
                    )

    def test_cross_engine_token_rejected(self):
        store = self._token_env()
        root = SubjectSet("n", "root", "r")
        host = ExpandEngine(store)
        snap = SnapshotExpandEngine(SnapshotManager(store))
        host_tok = host.build_tree_page(
            root, max_depth=5, page_size=1
        ).next_page_token
        snap_tok = snap.build_tree_page(
            root, max_depth=5, page_size=1
        ).next_page_token
        assert host_tok and snap_tok
        with pytest.raises(ErrMalformedPageToken):
            snap.build_tree_page(
                root, max_depth=5, page_size=1, page_token=host_tok
            )
        with pytest.raises(ErrMalformedPageToken):
            host.build_tree_page(
                root, max_depth=5, page_size=1, page_token=snap_tok
            )

    def test_stale_version_token_rejected(self):
        # the cursor pins the data version it was cut at; a write in
        # between supersedes it — fail closed, the client restarts
        store = self._token_env()
        for name, eng in _engines(store):
            tok = eng.build_tree_page(
                SubjectSet("n", "root", "r"), max_depth=5, page_size=1
            ).next_page_token
            assert tok, name
            store.write_relation_tuples(
                t(f"n:root#r@(fresh-{name})")
            )
            with pytest.raises(ErrMalformedPageToken):
                eng.build_tree_page(
                    SubjectSet("n", "root", "r"),
                    max_depth=5,
                    page_size=1,
                    page_token=tok,
                )

    def test_token_roundtrip(self):
        pending = [([0, 2], ["n", "obj", "rel"], 4), ([1], ["n", "x", "y"], 2)]
        visited = ["n:a#b", "n:c#d"]
        tok = encode_expand_page_token("host", 7, pending, visited)
        got_pending, got_visited = decode_expand_page_token(tok, "host", 7)
        assert got_pending == [tuple(p) for p in pending] or got_pending == [
            (list(path), ref, rest) for path, ref, rest in pending
        ]
        assert got_visited == visited
        with pytest.raises(ErrMalformedPageToken):
            decode_expand_page_token(tok, "snap", 7)
        with pytest.raises(ErrMalformedPageToken):
            decode_expand_page_token(tok, "host", 8)


class TestPatchErrors:
    def _tree(self):
        return Tree(
            type=NodeType.UNION,
            subject=SubjectSet("n", "o", "r"),
            children=[Tree(type=NodeType.LEAF, subject=SubjectID("u1"))],
        )

    def test_empty_path_rejected(self):
        with pytest.raises(ErrMalformedInput):
            apply_expand_patches(self._tree(), [([], self._tree())])

    def test_unresolvable_path_rejected(self):
        for path in ([5], [0, 0], [-1]):
            with pytest.raises(ErrMalformedInput):
                apply_expand_patches(self._tree(), [(path, self._tree())])
