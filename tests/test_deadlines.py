"""Deadline propagation drills (ISSUE 4): a caller's budget is enforced at
every stage of the serving plane, and the enforcement is observable.

- dead-on-arrival checks are rejected at admission (no engine work)
- entries whose deadline passes while queued/staged are culled at the next
  stage boundary (dispatch / encode / launch / decode), each cull tallied
  per stage on pipeline_stats() and keto_deadline_expired_total
- a client disconnect (future cancelled) frees the batch slot the same way
- the breaker's host-oracle fallback skips re-answering expired rows
- transports map the typed error: REST 504, gRPC DEADLINE_EXCEEDED
"""

import threading
import time
from concurrent.futures import CancelledError, Future
from types import SimpleNamespace

import grpc
import pytest

from keto_tpu.api import acl_pb2, check_service_pb2
from keto_tpu.api.rest import DEADLINE_HEADER, _json_error, deadline_from_headers
from keto_tpu.api.services import CheckServicer
from keto_tpu.engine.batcher import CheckBatcher
from keto_tpu.engine.fallback import DeviceFallbackEngine, _FallbackAnswered
from keto_tpu.faults import FAULTS
from keto_tpu.relationtuple.definitions import RelationTuple, SubjectID
from keto_tpu.telemetry import MetricsRegistry
from keto_tpu.utils.errors import DeadlineExceeded, ErrMalformedInput


@pytest.fixture(autouse=True)
def _clean_faults():
    FAULTS.reset()
    yield
    FAULTS.reset()


def _tup(i: int = 0) -> RelationTuple:
    return RelationTuple(
        namespace="n", object=f"o{i}", relation="view",
        subject=SubjectID(id="alice"),
    )


class _CountEngine:
    def __init__(self):
        self.calls = 0

    def batch_check(self, requests, max_depth=0, depths=None):
        self.calls += 1
        return [True] * len(requests)

    def subject_is_allowed(self, requested, max_depth=0):
        # the host-oracle shape the breaker fallback uses for per-row depths
        self.calls += 1
        return True


class _GateEngine:
    """Blocks every batch on an event — holds the dispatcher mid-flight so
    queue states (and what happens to entries stuck behind them) are
    controllable."""

    def __init__(self):
        self.gate = threading.Event()
        self.calls = 0

    def batch_check(self, requests, max_depth=0, depths=None):
        self.calls += 1
        self.gate.wait(timeout=10)
        return [True] * len(requests)


class _FakeEncoded:
    version = 0

    def __init__(self, requests):
        self.requests = list(requests)
        self.released = False

    def keys(self):
        return [(r.object, 0, 0) for r in self.requests]

    def compact(self, keep):
        self.requests = [self.requests[i] for i in keep]

    def release(self):
        self.released = True


class _SplitEngine:
    """Split encode/launch/decode engine with deterministic True answers,
    recording the staged batch and launch count so cull/compact behavior
    is assertable."""

    def __init__(self):
        self.last_enc = None
        self.launches = 0

    def pipeline_supported(self):
        return True

    def encode_batch(self, requests, max_depth=0, depths=None):
        self.last_enc = _FakeEncoded(requests)
        return self.last_enc

    def launch_encoded(self, enc):
        self.launches += 1
        return enc

    def decode_launched(self, launched):
        return [True] * len(launched.requests)

    def batch_check(self, requests, max_depth=0, depths=None):
        return [True] * len(requests)


def _pipelined(engine, metrics=None):
    return CheckBatcher(
        engine, window_s=0, metrics=metrics,
        pipeline_depth=2, encode_workers=1,
    )


def _enqueue(b, entries):
    """Append raw (tuple, depth, Future, deadline) entries atomically so
    they drain as ONE batch — the white-box seam for staging an entry in
    the pipe whose caller never races the stage cull."""
    futures = []
    with b._cv:
        for tup, depth, deadline in entries:
            f = Future()
            futures.append(f)
            b._queue.append((tup, depth, f, time.perf_counter(), deadline))
        b._cv.notify()
    return futures


class TestAdmission:
    def test_dead_on_arrival_never_reaches_engine(self):
        eng = _CountEngine()
        m = MetricsRegistry()
        b = CheckBatcher(eng, window_s=0, metrics=m)
        try:
            with pytest.raises(DeadlineExceeded):
                b.check(_tup(), deadline=time.monotonic() - 0.01)
            assert eng.calls == 0
            assert b.pipeline_stats()["deadline_expired"] == {"admission": 1}
            assert b._m_deadline.labels(stage="admission").value == 1
        finally:
            b.close()

    def test_batch_path_rejects_dead_on_arrival(self):
        eng = _CountEngine()
        b = CheckBatcher(eng, window_s=0)
        try:
            with pytest.raises(DeadlineExceeded):
                b.check_batch(
                    [_tup(0), _tup(1)], deadline=time.monotonic() - 0.01
                )
            assert eng.calls == 0
        finally:
            b.close()

    def test_live_deadline_is_served(self):
        b = CheckBatcher(_CountEngine(), window_s=0)
        try:
            assert b.check(_tup(), deadline=time.monotonic() + 5) is True
        finally:
            b.close()


class TestStageCulls:
    def test_expiry_while_queued_culled_at_dispatch(self):
        eng = _GateEngine()
        m = MetricsRegistry()
        b = CheckBatcher(eng, window_s=0, metrics=m)
        try:
            t1 = threading.Thread(target=lambda: b.check(_tup(0)), daemon=True)
            t1.start()
            deadline = time.time() + 5
            while eng.calls < 1 and time.time() < deadline:
                time.sleep(0.005)
            assert eng.calls == 1  # dispatcher held mid-flight
            # entry 2 waits behind it with a budget that runs out queued
            (f,) = _enqueue(b, [(_tup(1), 0, time.monotonic() + 0.05)])
            time.sleep(0.1)
            eng.gate.set()
            assert isinstance(f.exception(timeout=5), DeadlineExceeded)
            t1.join(timeout=5)
            assert eng.calls == 1  # the dead entry never dispatched
            assert b.pipeline_stats()["deadline_expired"] == {"dispatch": 1}
        finally:
            eng.gate.set()
            b.close()

    def test_client_disconnect_culled_at_dispatch(self):
        eng = _GateEngine()
        b = CheckBatcher(eng, window_s=0, metrics=MetricsRegistry())
        try:
            t1 = threading.Thread(target=lambda: b.check(_tup(0)), daemon=True)
            t1.start()
            deadline = time.time() + 5
            while eng.calls < 1 and time.time() < deadline:
                time.sleep(0.005)
            entries, err = [], []

            def caller():
                try:
                    b.check(_tup(1), entry_hook=entries.append)
                except BaseException as e:
                    err.append(e)

            t2 = threading.Thread(target=caller, daemon=True)
            t2.start()
            while not entries and time.time() < deadline:
                time.sleep(0.005)
            # the transport's disconnect hook: cancel the queued entry
            assert entries[0].cancel() is True
            eng.gate.set()
            t2.join(timeout=5)
            t1.join(timeout=5)
            while (
                b.pipeline_stats()["cancelled"].get("dispatch", 0) < 1
                and time.time() < deadline
            ):
                time.sleep(0.005)
            assert b.pipeline_stats()["cancelled"] == {"dispatch": 1}
            assert eng.calls == 1  # slot freed, engine never paid
            assert isinstance(err[0], CancelledError)
        finally:
            eng.gate.set()
            b.close()

    def test_deadline_mid_flight_raises_typed_and_cancels(self):
        eng = _GateEngine()
        b = CheckBatcher(eng, window_s=0)
        try:
            with pytest.raises(DeadlineExceeded):
                b.check(_tup(), deadline=time.monotonic() + 0.1)
            assert eng.calls == 1  # dispatched live, caller gave up waiting
        finally:
            eng.gate.set()
            b.close()

    def test_expired_entry_culled_at_encode(self):
        class _GateSplit(_SplitEngine):
            def __init__(self):
                super().__init__()
                self.gate = threading.Event()

            def encode_batch(self, requests, max_depth=0, depths=None):
                enc = super().encode_batch(requests, max_depth, depths)
                self.gate.wait(timeout=10)
                return enc

        eng = _GateSplit()
        b = _pipelined(eng, metrics=MetricsRegistry())
        try:
            (f1,) = _enqueue(b, [(_tup(0), 0, None)])
            deadline = time.time() + 5
            while eng.last_enc is None and time.time() < deadline:
                time.sleep(0.005)
            # encode worker held; entry 2's budget runs out in the queue
            (f2,) = _enqueue(b, [(_tup(1), 0, time.monotonic() + 0.05)])
            time.sleep(0.1)
            eng.gate.set()
            assert f1.result(timeout=10) is True
            assert isinstance(f2.exception(timeout=10), DeadlineExceeded)
            assert b.pipeline_stats()["deadline_expired"] == {"encode": 1}
        finally:
            b.close()

    def test_expired_row_culled_at_launch_compacts_buffers(self):
        eng = _SplitEngine()
        m = MetricsRegistry()
        b = _pipelined(eng, metrics=m)
        try:
            FAULTS.arm_slow("batcher.launch_slow", sleep_ms=400)
            f_dead, f_live = _enqueue(b, [
                (_tup(0), 0, time.monotonic() + 0.15),
                (_tup(1), 0, None),
            ])
            assert f_live.result(timeout=10) is True
            assert isinstance(f_dead.exception(timeout=10), DeadlineExceeded)
            assert b.pipeline_stats()["deadline_expired"] == {"launch": 1}
            assert b._m_deadline.labels(stage="launch").value == 1
            # the staged device buffers were compacted to the live row
            # before the kernel dispatch — the dead row never rode it
            assert [r.object for r in eng.last_enc.requests] == ["o1"]
            assert eng.launches == 1
        finally:
            b.close()

    def test_fully_expired_batch_released_without_launch(self):
        eng = _SplitEngine()
        b = _pipelined(eng, metrics=MetricsRegistry())
        try:
            FAULTS.arm_slow("batcher.launch_slow", sleep_ms=300)
            (f,) = _enqueue(b, [(_tup(0), 0, time.monotonic() + 0.1)])
            assert isinstance(f.exception(timeout=10), DeadlineExceeded)
            deadline = time.time() + 5
            while not eng.last_enc.released and time.time() < deadline:
                time.sleep(0.005)
            assert eng.last_enc.released is True
            assert eng.launches == 0  # no kernel time for a dead batch
            assert b.pipeline_stats()["batches_in_pipeline"] == 0
        finally:
            b.close()

    def test_expired_row_failed_typed_at_decode_results_stay_aligned(self):
        eng = _SplitEngine()
        b = _pipelined(eng, metrics=MetricsRegistry())
        try:
            FAULTS.arm_slow("batcher.decode_slow", sleep_ms=400)
            f_dead, f_live = _enqueue(b, [
                (_tup(0), 0, time.monotonic() + 0.15),
                (_tup(1), 0, None),
            ])
            # the kernel already ran for both rows (decode is post-launch),
            # but the dead caller is failed typed instead of being handed a
            # result after the blocking materialization
            assert f_live.result(timeout=10) is True
            assert isinstance(f_dead.exception(timeout=10), DeadlineExceeded)
            assert b.pipeline_stats()["deadline_expired"] == {"decode": 1}
            assert eng.launches == 1  # too late to save device time here
        finally:
            b.close()


class TestFallbackSkips:
    def test_fallback_skips_rows_whose_deadline_passed(self):
        m = MetricsRegistry()
        fb = DeviceFallbackEngine(
            _CountEngine(), lambda: _CountEngine(), metrics=m
        )
        out = fb._fallback_check(
            [_tup(0), _tup(1)], 0, None,
            deadlines=[time.monotonic() - 1, None],
        )
        assert out == [None, True]
        assert fb._m_deadline_skips.value == 1

    def test_launch_failure_fallback_honors_staged_deadlines(self):
        class _Boom:
            def launch_encoded(self, enc):
                raise RuntimeError("sick chip")

        m = MetricsRegistry()
        fb = DeviceFallbackEngine(_Boom(), lambda: _CountEngine(), metrics=m)
        enc = _FakeEncoded([_tup(0), _tup(1)])
        enc.depths = [0, 0]
        enc.deadlines = [time.monotonic() - 1, None]
        answered = fb.launch_encoded(enc)
        assert isinstance(answered, _FallbackAnswered)
        assert answered.results == [None, True]
        assert enc.released is True
        assert fb._m_deadline_skips.value == 1


class TestTransportMapping:
    def test_rest_header_parsing(self):
        assert deadline_from_headers(SimpleNamespace(headers={})) is None
        before = time.monotonic()
        dl = deadline_from_headers(
            SimpleNamespace(headers={DEADLINE_HEADER: "250"})
        )
        assert before + 0.2 < dl < time.monotonic() + 0.3
        with pytest.raises(ErrMalformedInput):
            deadline_from_headers(
                SimpleNamespace(headers={DEADLINE_HEADER: "soon"})
            )
        with pytest.raises(ErrMalformedInput):
            deadline_from_headers(
                SimpleNamespace(headers={DEADLINE_HEADER: "-5"})
            )

    def test_rest_maps_to_504(self):
        err = DeadlineExceeded()
        assert err.status_code == 504
        assert err.grpc_code == "DEADLINE_EXCEEDED"
        resp = _json_error(err)
        assert resp.status == 504
        # a request out of budget is not a shed request: retrying with the
        # same deadline is pointless, so no Retry-After invitation
        assert "Retry-After" not in resp.headers

    def test_grpc_expired_rpc_aborts_deadline_exceeded(self):
        class _Abort(Exception):
            pass

        class _Ctx:
            def __init__(self, remaining):
                self._remaining = remaining
                self.callbacks = []
                self.code = None

            def time_remaining(self):
                return self._remaining

            def add_callback(self, cb):
                self.callbacks.append(cb)
                return True

            def set_trailing_metadata(self, md):
                pass

            def abort(self, code, details):
                self.code = code
                raise _Abort(details)

        eng = _CountEngine()
        b = CheckBatcher(eng, window_s=0)
        try:
            svc = CheckServicer(b, snaptoken_fn=lambda: "7")
            req = check_service_pb2.CheckRequest(
                namespace="n", object="o0", relation="view",
                subject=acl_pb2.Subject(id="alice"),
            )
            ctx = _Ctx(remaining=-0.25)  # client deadline already passed
            with pytest.raises(_Abort):
                svc.Check(req, ctx)
            assert ctx.code is grpc.StatusCode.DEADLINE_EXCEEDED
            assert eng.calls == 0
            # a live RPC answers normally through the same path
            live = _Ctx(remaining=5.0)
            resp = svc.Check(req, live)
            assert resp.allowed is True
            assert resp.snaptoken == "7"
            # the termination callback was registered for disconnect culls
            assert live.callbacks
        finally:
            b.close()
