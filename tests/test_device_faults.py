"""Device-fault & memory-pressure robustness plane (engine/fallback.py,
engine/hbm.py, driver/registry.py DeviceSupervisor).

- typed XLA error classification (oom / device_lost / compile_fail /
  transient), including injected-fault sites
- OOM batch bisection: parity-exact against the unsplit host oracle under
  fuzzed batch sizes and armed-OOM counts (random split trees), unicode
  vocab, and encoded-cache-hit interleaving — with ZERO host-fallback
  escalations
- compile-failure quarantine: the failing (bucket, snapshot) shape routes
  to the oracle without opening the circuit for every other shape
- device-lost -> supervised backend failover -> bounded recovery, end to
  end through the registry
- breaker half-open re-probe jitter: deterministic under an injected rng,
  exponential cooldown growth capped
- HBM admission control: budget calibration from device memory stats,
  chunk clamping, reserve/release accounting, rebuild gating, and the
  no-device-stats degrade to admission-off
"""

import random
import threading
import time

import pytest

from keto_tpu.engine import CheckEngine
from keto_tpu.engine.device import DeviceCheckEngine
from keto_tpu.engine.fallback import (
    DeviceFallbackEngine,
    classify_device_error,
)
from keto_tpu.engine.hbm import HbmAdmission
from keto_tpu.faults import FAULTS, FaultInjected
from keto_tpu.graph import SnapshotManager
from keto_tpu.relationtuple import RelationTuple
from keto_tpu.store import InMemoryTupleStore


def t(s: str) -> RelationTuple:
    return RelationTuple.from_string(s)


@pytest.fixture(autouse=True)
def _clean_faults():
    FAULTS.reset()
    yield
    FAULTS.reset()


def make_breaker(store, **kw):
    mgr = SnapshotManager(store)
    dev = DeviceCheckEngine(mgr, max_depth=5, mode="scatter")
    breaker = DeviceFallbackEngine(
        dev,
        fallback_factory=lambda: CheckEngine(store, max_depth=5),
        failure_threshold=3,
        cooldown_s=0.1,
        **kw,
    )
    return dev, breaker


# unicode vocab: node ids must survive encode -> split -> re-encode even
# when the key strings are multi-byte (a naive byte-offset split corrupts)
_UNI_OBJS = ["документ", "予約-α", "ficha-ñ", "plain"]
_UNI_USERS = ["алиса", "ユーザー1", "böb", "mallory"]


def _seed_unicode_graph(store):
    tuples = []
    for i, obj in enumerate(_UNI_OBJS):
        tuples.append(t(f"n:{obj}#view@(n:группа{i % 2}#member)"))
    tuples.append(t("n:группа0#member@алиса"))
    tuples.append(t("n:группа1#member@ユーザー1"))
    tuples.append(t("n:plain#view@böb"))
    store.write_relation_tuples(*tuples)


def _request_pool(rng, k):
    """(requests, unused) — mixed hits/misses over the unicode graph."""
    reqs = []
    for _ in range(k):
        obj = _UNI_OBJS[rng.randrange(len(_UNI_OBJS))]
        user = _UNI_USERS[rng.randrange(len(_UNI_USERS))]
        reqs.append(t(f"n:{obj}#view@{user}"))
    return reqs


class TestClassification:
    @pytest.mark.parametrize(
        "msg,kind",
        [
            ("RESOURCE_EXHAUSTED: out of memory allocating 2GB", "oom"),
            ("XLA error: failed to allocate buffer", "oom"),
            ("DEVICE_LOST: tpu rebooted underneath us", "device_lost"),
            ("backend reported device lost", "device_lost"),
            ("Mosaic compilation failure: unsupported op", "compile_fail"),
            ("something unrecognized went wrong", "transient"),
        ],
    )
    def test_message_taxonomy(self, msg, kind):
        assert classify_device_error(RuntimeError(msg)) == kind

    def test_injected_fault_sites(self):
        assert classify_device_error(FaultInjected("device.oom")) == "oom"
        assert (
            classify_device_error(FaultInjected("device.lost"))
            == "device_lost"
        )
        assert (
            classify_device_error(FaultInjected("device.compile_fail"))
            == "compile_fail"
        )
        # the PR2-era compile_error site keeps breaker (transient)
        # semantics for back-compat with the existing fault matrix
        assert (
            classify_device_error(FaultInjected("device.compile_error"))
            == "transient"
        )


class TestOomBisection:
    def _roundtrip(self, breaker, reqs):
        enc = breaker.encode_batch(reqs)
        launched = breaker.launch_encoded(enc)
        return [bool(v) for v in breaker.decode_launched(launched)]

    def test_parity_fuzz_random_splits(self):
        """Fuzz batch sizes and armed-OOM counts: every bisection tree
        (odd splits, nested re-splits) must answer exactly like the
        unsplit host oracle, with the circuit closed and the host
        fallback engine never even constructed."""
        store = InMemoryTupleStore()
        _seed_unicode_graph(store)
        oracle = CheckEngine(store, max_depth=5)
        _, breaker = make_breaker(store)
        rng = random.Random(11)
        for trial, size in enumerate([2, 3, 5, 17, 33, 64, 120]):
            reqs = _request_pool(rng, size)
            want = [oracle.subject_is_allowed(r) for r in reqs]
            # the bisection's first-half path halves per armed fire, so k
            # fires need size >= 2^k — cap k so NO trial can bottom out at
            # an unsplittable single row (which would escalate, correctly,
            # to the host oracle; the zero-escalation variant is the point
            # of THIS test)
            times = max(1, min(1 + trial % 3, size.bit_length() - 1))
            FAULTS.arm("device.oom", times=times)
            got = self._roundtrip(breaker, reqs)
            assert got == want, f"size={size}"
            assert not FAULTS.armed("device.oom")
        assert not breaker.circuit_open()
        # zero host-fallback escalations: the oracle was never built
        assert breaker._fallback is None

    def test_single_row_oom_cannot_split(self):
        """n=1 cannot bisect: the row still gets a CORRECT answer (host
        oracle fallthrough) — never a wrong one."""
        store = InMemoryTupleStore()
        _seed_unicode_graph(store)
        _, breaker = make_breaker(store)
        FAULTS.arm("device.oom")
        got = self._roundtrip(breaker, [t("n:plain#view@böb")])
        assert got == [True]

    def test_persistent_oom_exhausts_depth_then_oracle(self):
        """Every launch OOMs: bisection bottoms out at max_bisect_depth
        and the batch falls through to the oracle — correct answers,
        bounded work."""
        store = InMemoryTupleStore()
        _seed_unicode_graph(store)
        oracle = CheckEngine(store, max_depth=5)
        _, breaker = make_breaker(store)
        rng = random.Random(3)
        reqs = _request_pool(rng, 32)
        want = [oracle.subject_is_allowed(r) for r in reqs]
        FAULTS.arm("device.oom", times=10_000)
        got = self._roundtrip(breaker, reqs)
        assert got == want
        FAULTS.reset()

    def test_cache_hit_interleaving(self):
        """Encoded-cache hits compact the batch before launch; the OOM
        bisection of the compacted MISS rows must still merge back into
        a parity-exact full answer."""
        from keto_tpu.engine.batcher import CheckBatcher
        from keto_tpu.relationtuple.columns import CheckColumns

        store = InMemoryTupleStore()
        _seed_unicode_graph(store)
        oracle = CheckEngine(store, max_depth=5)
        _, breaker = make_breaker(store)
        batcher = CheckBatcher(
            breaker, max_batch=256, encoded_cache_size=1024
        )
        try:
            rng = random.Random(5)
            warm = _request_pool(rng, 24)
            cols_w = CheckColumns(
                ["n"] * len(warm),
                [r.object for r in warm],
                ["view"] * len(warm),
                subject_ids=[r.subject.id for r in warm],
            ).validate()
            batcher.check_batch_columnar(cols_w, 5)  # populate the cache
            mixed = warm[:12] + _request_pool(rng, 36)  # hits + fresh rows
            want = [oracle.subject_is_allowed(r) for r in mixed]
            cols_m = CheckColumns(
                ["n"] * len(mixed),
                [r.object for r in mixed],
                ["view"] * len(mixed),
                subject_ids=[r.subject.id for r in mixed],
            ).validate()
            FAULTS.arm("device.oom", times=2)
            got = batcher.check_batch_columnar(cols_m, 5)
            assert [bool(v) for v in got] == want
            assert not breaker.circuit_open()
        finally:
            batcher.close()


class TestCompileQuarantine:
    def test_quarantine_absorbs_shape_without_tripping(self):
        store = InMemoryTupleStore()
        _seed_unicode_graph(store)
        oracle = CheckEngine(store, max_depth=5)
        _, breaker = make_breaker(store)
        rng = random.Random(9)
        reqs = _request_pool(rng, 20)
        want = [oracle.subject_is_allowed(r) for r in reqs]
        FAULTS.arm("device.compile_fail")
        enc = breaker.encode_batch(reqs)
        got = breaker.decode_launched(breaker.launch_encoded(enc))
        assert [bool(v) for v in got] == want
        assert not breaker.circuit_open()
        q = breaker.quarantine_snapshot()
        assert len(q) == 1 and q[0]["bucket"] == 32
        # the same shape now routes straight to the oracle (no fault
        # armed, but the quarantine remembers) — still correct
        enc2 = breaker.encode_batch(reqs)
        got2 = breaker.decode_launched(breaker.launch_encoded(enc2))
        assert [bool(v) for v in got2] == want
        # a DIFFERENT bucket is untouched by the quarantine: the device
        # still serves it
        small = reqs[:4]
        enc3 = breaker.encode_batch(small)
        got3 = breaker.decode_launched(breaker.launch_encoded(enc3))
        assert [bool(v) for v in got3] == want[:4]
        assert not breaker.circuit_open()


class TestBreakerJitterAndDeviceLost:
    def _ticking(self, breaker_kw=None):
        fake = [0.0]
        store = InMemoryTupleStore()
        store.write_relation_tuples(t("n:o#view@u"))
        _, breaker = make_breaker(
            store, clock=lambda: fake[0], **(breaker_kw or {})
        )
        return fake, breaker

    def test_jitter_deterministic_under_injected_rng(self):
        opens = []
        for _ in range(2):
            fake, breaker = self._ticking(
                {"rng": random.Random(42), "jitter_frac": 0.25}
            )
            breaker.failure_threshold = 1
            breaker._record_failure(RuntimeError("boom"))
            opens.append(breaker._open_until)
        assert opens[0] == opens[1]  # same rng seed -> same jitter
        # jittered open window lands in [cooldown, cooldown * 1.25)
        assert 0.1 <= opens[0] < 0.1 * 1.25

    def test_cooldown_doubles_and_caps(self):
        from keto_tpu.engine.fallback import _COOLDOWN_CAP_S

        fake, breaker = self._ticking(
            {"rng": random.Random(1), "jitter_frac": 0.0}
        )
        breaker.failure_threshold = 1
        breaker._record_failure(RuntimeError("boom"))
        assert breaker._cooldown_s == pytest.approx(0.1)
        for _ in range(16):  # re-failures while open: exponential, capped
            breaker._record_failure(RuntimeError("boom"))
        assert breaker._cooldown_s == _COOLDOWN_CAP_S

    def test_device_lost_forces_open_and_notifies(self):
        lost = []
        store = InMemoryTupleStore()
        store.write_relation_tuples(t("n:o#view@u"))
        _, breaker = make_breaker(store, on_device_lost=lost.append)
        assert breaker.failure_threshold == 3
        breaker._note_failure(RuntimeError("DEVICE_LOST: gone"))
        # one device_lost opens the circuit immediately — no waiting for
        # the consecutive-failure threshold
        assert breaker.circuit_open()
        assert len(lost) == 1

    def test_force_probe_collapses_open_window(self):
        fake, breaker = self._ticking({"rng": random.Random(2)})
        breaker.failure_threshold = 1
        breaker._record_failure(RuntimeError("boom"))
        assert breaker.circuit_open()
        breaker.force_probe()
        # the next batch may now probe: _use_primary() flips to probing
        assert breaker._use_primary()


class TestFailoverEndToEnd:
    def test_device_lost_failover_recovery_drill(self):
        """The acceptance drill: seeded device.lost keeps serving through
        the host oracle, the supervisor re-probes and returns serving to
        device mode within a bounded window, and the whole episode is
        visible in /debug/device and the flight recorder."""
        from keto_tpu.driver.config import Config
        from keto_tpu.driver.registry import Registry
        from keto_tpu.relationtuple.columns import CheckColumns

        cfg = Config(
            values={
                "namespaces": [{"id": 1, "name": "n"}],
                "log": {"level": "error"},
                "engine": {
                    "mode": "device",
                    "max_batch": 128,
                    "cache_size": 0,
                    "encoded_cache_size": 0,
                    "fallback_cooldown_ms": 100,
                    "failover": {
                        "probe_mode": "inproc",
                        "probe_interval_s": 0.05,
                    },
                },
            }
        )
        reg = Registry(cfg)
        store = reg.store()
        objs = [f"ok{i}" for i in range(16)]
        store.write_relation_tuples(
            *[
                RelationTuple.from_string(f"n:{o}#view@alice")
                for o in objs
            ]
        )
        checker = reg.checker()
        supervisor = reg.device_supervisor()
        breaker = reg._engine_breaker
        try:
            rows = objs + ["ghost0", "ghost1"]
            want = [True] * len(objs) + [False, False]
            cols = CheckColumns(
                ["n"] * len(rows),
                rows,
                ["view"] * len(rows),
                subject_ids=["alice"] * len(rows),
            ).validate()
            FAULTS.arm("device.lost")
            # the lost batch itself: answered by the oracle, still exact
            assert [
                bool(v) for v in checker.check_batch_columnar(cols, 5)
            ] == want
            deadline = time.monotonic() + 15.0
            status = None
            while time.monotonic() < deadline:
                status = supervisor.status()
                if status["failovers"] >= 1 and not status["recovering"]:
                    break
                time.sleep(0.02)
            assert status is not None and status["failovers"] >= 1
            assert not status["recovering"], status
            assert status["last_recovery_s"] < 15.0
            events = [e["event"] for e in status["timeline"]]
            assert "device_lost" in events and "recovered" in events
            # post-recovery: the forced half-open probe lets the next
            # batch close the circuit and serve from the device again
            assert [
                bool(v) for v in checker.check_batch_columnar(cols, 5)
            ] == want
            assert not breaker.circuit_open()
            status = reg._device_status()
            assert status["backend"] == "cpu"
            assert status["supervisor"]["failovers"] >= 1
            flight = reg.flight()
            kinds = [r.get("kind") for r in flight.records(100)]
            assert "device_failover" in kinds
        finally:
            checker.close()
            supervisor.stop()

    def test_probe_hang_counts_as_failed_attempt(self):
        """backend.probe_hang: the supervisor's probe child 'hangs' and is
        killed — recovery happens on the NEXT probe, never a wedge."""
        from keto_tpu.driver.registry import DeviceSupervisor

        class _Eng:
            interpret = False

            def reset_residency(self):
                pass

            def warmup(self, n):
                pass

        sup = DeviceSupervisor(
            _Eng(),
            probe_mode="inproc",
            probe_interval_s=0.01,
            max_backoff_s=0.05,
        )
        FAULTS.arm("backend.probe_hang")
        sup.notify_device_lost(RuntimeError("DEVICE_LOST"))
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            st = sup.status()
            if not st["recovering"]:
                break
            time.sleep(0.02)
        st = sup.status()
        assert not st["recovering"]
        probe_events = [
            e for e in st["timeline"] if e["event"] == "probe"
        ]
        assert any(not e["ok"] for e in probe_events)  # the hung one
        assert any(e["ok"] for e in probe_events)  # the recovery one
        sup.stop()


class _FakeDevstats:
    def __init__(self, limit=1_000_000, peak=0):
        self.limit = limit
        self.peak = peak

    def sample_devices(self):
        if self.limit is None:
            return [{"platform": "cpu", "memory_stats": None}]
        return [
            {
                "platform": "tpu",
                "memory_stats": {
                    "bytes_in_use": 0,
                    "bytes_limit": self.limit,
                    "peak_bytes_in_use": self.peak,
                },
            }
        ]


class TestHbmAdmission:
    def test_no_device_stats_disables_admission(self):
        hbm = HbmAdmission(devstats=_FakeDevstats(limit=None))
        assert hbm.budget_bytes() is None
        assert hbm.clamp_rows(4096) == 4096
        assert hbm.reserve(128, 1) == 0  # free token
        hbm.release(0)  # no-op
        assert hbm.wait_for_headroom(timeout_s=0.0)

    def test_budget_and_clamp(self):
        hbm = HbmAdmission(
            budget_frac=0.5,
            bytes_per_row=100,
            devstats=_FakeDevstats(limit=1_000_000),
        )
        assert hbm.budget_bytes() == pytest.approx(500_000)
        # 500k budget / 100 B per row = 5000 rows fit: no clamp at 4096
        assert hbm.clamp_rows(4096) == 4096
        # reserve a big shape, then the same ask must be pre-split
        tok = hbm.reserve(4096, 1)
        assert tok != 0
        assert hbm.clamp_rows(4096) < 4096
        hbm.release(tok)
        assert hbm.clamp_rows(4096) == 4096
        assert hbm.snapshot()["inflight_batches"] == 0

    def test_reserve_release_accounting(self):
        hbm = HbmAdmission(
            bytes_per_row=100, devstats=_FakeDevstats(limit=1_000_000)
        )
        t1 = hbm.reserve(128, 1)
        t2 = hbm.reserve(256, 1)
        snap = hbm.snapshot()
        assert snap["inflight_batches"] == 2
        assert snap["inflight_bytes"] == pytest.approx(38_400)
        hbm.release(t1)
        hbm.release(t1)  # double release is a no-op
        hbm.release(t2)
        assert hbm.snapshot()["inflight_bytes"] == 0.0

    def test_rebuild_gate_blocks_until_headroom(self):
        hbm = HbmAdmission(
            budget_frac=1.0,
            bytes_per_row=1000,
            devstats=_FakeDevstats(limit=100_000),
        )
        tok = hbm.reserve(100, 1)  # 100k modeled = the whole budget
        assert not hbm.wait_for_headroom(frac=0.5, timeout_s=0.05)
        done = []

        def _release_later():
            time.sleep(0.05)
            hbm.release(tok)
            done.append(True)

        threading.Thread(target=_release_later, daemon=True).start()
        assert hbm.wait_for_headroom(frac=0.5, timeout_s=5.0)
        assert done

    def test_peak_delta_learns_model(self):
        stats = _FakeDevstats(limit=1_000_000, peak=0)
        hbm = HbmAdmission(bytes_per_row=100, devstats=stats)
        tok = hbm.reserve(128, 1)
        stats.peak = 64_000  # the batch pushed the high-water mark up
        hbm.release(tok)
        # the learned per-shape model replaces the per-row estimate
        assert hbm.modeled_bytes(128, 1) == pytest.approx(64_000)
