"""Write-path freshness of the closure engine: incremental closure updates,
bounded-staleness serving with background rebuilds, and snaptoken honesty.

The reference stubs snapshot tokens ("not yet implemented",
/root/reference/internal/check/handler.go:182); here bounded freshness is the
real Zanzibar zookie contract: a check may be answered at a slightly older
store version, and the response names that version.
"""

import time

import numpy as np
import pytest

from keto_tpu.engine import CheckEngine
from keto_tpu.engine.closure import ClosureCheckEngine
from keto_tpu.graph import SnapshotManager
from keto_tpu.relationtuple import RelationTuple
from keto_tpu.store import InMemoryTupleStore

from test_device_engines import random_store


def t(s: str) -> RelationTuple:
    return RelationTuple.from_string(s)


def _wait_until(pred, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.01)
    return False


class TestIncrementalClosure:
    def test_appended_interior_edge_updates_in_place(self):
        store = InMemoryTupleStore()
        store.write_relation_tuples(
            t("n:a#r@(n:b#r)"),
            t("n:b#r@(n:c#r)"),
            t("n:c#r@u1"),
        )
        mgr = SnapshotManager(store)
        eng = ClosureCheckEngine(mgr, max_depth=8)
        assert eng.subject_is_allowed(t("n:a#r@u1"))
        full0 = eng.n_full_builds
        assert full0 >= 1 and eng.n_incremental_builds == 0

        # c#r -> b#r: both endpoints already interior. Since round 4 the
        # write OVERLAY absorbs this with an in-place O(M^2) D patch — no
        # rebuild of any kind (engine/overlay.py)
        store.write_relation_tuples(t("n:c#r@(n:b#r)"))
        assert eng.subject_is_allowed(t("n:c#r@u1"))
        assert eng.n_incremental_builds == 0
        assert eng.n_full_builds == full0

        # the cycle b -> c -> b must now resolve both ways
        assert eng.subject_is_allowed(t("n:b#r@(n:b#r)"))
        assert eng.subject_is_allowed(t("n:c#r@(n:c#r)"))

    def test_new_interior_node_grows_without_rebuild(self):
        store = InMemoryTupleStore()
        store.write_relation_tuples(
            t("n:a#r@(n:b#r)"), t("n:b#r@u1")
        )
        mgr = SnapshotManager(store)
        eng = ClosureCheckEngine(mgr, max_depth=8)
        eng.subject_is_allowed(t("n:a#r@u1"))
        full0 = eng.n_full_builds

        # a#r gains an incoming edge -> becomes interior. Since round 4
        # the overlay grows it into D's reserved padding in place
        # (engine/overlay.py _grow_interior) — no rebuild
        store.write_relation_tuples(t("n:x#q@(n:a#r)"))
        assert eng.subject_is_allowed(t("n:x#q@u1"))
        assert eng.n_full_builds == full0
        assert eng.served_version() == store.version

    @pytest.mark.parametrize("seed", range(3))
    def test_incremental_stream_matches_oracle(self, seed):
        """A stream of appended set->set edges between existing interior
        nodes must keep the closure bit-for-bit exact vs host BFS."""
        rng = np.random.default_rng(seed + 300)
        store = random_store(rng, n_objects=12, n_users=8, n_edges=120)
        mgr = SnapshotManager(store)
        eng = ClosureCheckEngine(mgr, max_depth=6)
        host = CheckEngine(store, max_depth=6)
        snap = mgr.snapshot()
        from keto_tpu.graph.interior import build_interior

        ig = build_interior(snap)
        if ig.m < 3:
            pytest.skip("graph too small to have interior pairs")
        keys = [snap.vocab.key(int(i)) for i in ig.interior_ids]
        eng.subject_is_allowed(t("n:o0#r0@u0"))  # prime the closure
        for _ in range(5):
            a = keys[rng.integers(len(keys))]
            b = keys[rng.integers(len(keys))]
            store.write_relation_tuples(
                RelationTuple.from_string(
                    f"{a[0]}:{a[1]}#{a[2]}@({b[0]}:{b[1]}#{b[2]})"
                )
            )
            reqs = []
            for _ in range(32):
                obj = f"o{rng.integers(12)}"
                rel = f"r{rng.integers(3)}"
                sub = f"u{rng.integers(8)}"
                reqs.append(t(f"n:{obj}#{rel}@{sub}"))
            expect = [host.subject_is_allowed(r) for r in reqs]
            assert eng.batch_check(reqs) == expect
        # round 4: the overlay absorbs the whole stream without rebuilds
        assert eng.n_incremental_builds == 0
        assert eng.n_full_builds == 1


class TestBoundedFreshness:
    def test_serves_stale_then_converges(self):
        store = InMemoryTupleStore()
        store.write_relation_tuples(t("n:obj#r@alice"))
        mgr = SnapshotManager(store)
        eng = ClosureCheckEngine(
            mgr, max_depth=5, freshness="bounded", rebuild_debounce_s=0.0
        )
        assert eng.subject_is_allowed(t("n:obj#r@alice"))
        v0 = eng.served_version()

        store.write_relation_tuples(t("n:obj#r@bob"))
        # the first check after the write must NOT stall on a rebuild: it
        # answers from the stale snapshot (served_version says which)
        eng.subject_is_allowed(t("n:obj#r@bob"))
        # ...and the background rebuild converges to the new version
        assert _wait_until(
            lambda: eng.served_version() == store.version
            and eng.subject_is_allowed(t("n:obj#r@bob"))
        )
        assert eng.served_version() > v0

    def test_no_stall_under_write_storm(self):
        """Checks stay fast while writes stream in: no check should ever
        pay a synchronous rebuild under bounded freshness."""
        store = InMemoryTupleStore()
        for i in range(50):
            store.write_relation_tuples(t(f"n:o{i}#r@(n:g{i % 7}#m)"))
        for i in range(7):
            store.write_relation_tuples(t(f"n:g{i}#m@alice"))
        mgr = SnapshotManager(store)
        eng = ClosureCheckEngine(
            mgr, max_depth=5, freshness="bounded", rebuild_debounce_s=0.0
        )
        eng.warmup()
        req = t("n:o1#r@alice")
        lat = []
        for i in range(60):
            store.write_relation_tuples(t(f"n:extra{i}#r@bob"))
            t0 = time.perf_counter()
            assert eng.subject_is_allowed(req)
            lat.append(time.perf_counter() - t0)
        # p95 bounded: stale serving means no check waits on a rebuild.
        # (generous bound — CI boxes are noisy; the failure mode being
        # guarded against is a multi-second synchronous closure rebuild)
        assert sorted(lat)[int(len(lat) * 0.95)] < 0.5
        assert _wait_until(lambda: eng.served_version() == store.version)

    def test_strong_freshness_is_read_your_writes(self):
        store = InMemoryTupleStore()
        mgr = SnapshotManager(store)
        eng = ClosureCheckEngine(mgr, max_depth=5, freshness="strong")
        assert not eng.subject_is_allowed(t("n:obj#r@alice"))
        store.write_relation_tuples(t("n:obj#r@alice"))
        assert eng.subject_is_allowed(t("n:obj#r@alice"))
        assert eng.served_version() == store.version

    def test_auto_is_strong_at_small_scale(self):
        store = InMemoryTupleStore()
        mgr = SnapshotManager(store)
        eng = ClosureCheckEngine(mgr, max_depth=5)  # freshness="auto"
        store.write_relation_tuples(t("n:obj#r@alice"))
        # tiny graph -> strong: immediately visible
        assert eng.subject_is_allowed(t("n:obj#r@alice"))
