"""Integrity plane tests: the ScrubDaemon's detect → quarantine →
repair ladder against every fault site, the anti-entropy digest math,
the freeze-under-SLO-burn discipline, and the ``keto doctor`` offline
fsck exit-code contract.

The end-to-end drills (fault injected against a real engine / WAL /
follower, detected within the cycle budget, auto-repaired, post-repair
state byte-identical to host truth) are gated in tools/scrub_gate.py;
these tests pin the component contracts the gate builds on.
"""

import json
import os

import numpy as np
import pytest
from click.testing import CliRunner

from keto_tpu.cli import cli
from keto_tpu.engine import CheckEngine
from keto_tpu.engine.cache import CheckResultCache
from keto_tpu.engine.closure import ClosureCheckEngine
from keto_tpu.engine.scrub import (
    ACTION_CACHE_FLUSH,
    ACTION_CHECKPOINT_REBUILD,
    ACTION_RESEED,
    ACTION_RESET_RESIDENCY,
    KIND_CHECKPOINT,
    KIND_DEVICE,
    KIND_REPLAY,
    KIND_WAL,
    ScrubDaemon,
)
from keto_tpu.faults import FAULTS
from keto_tpu.graph import SnapshotManager
from keto_tpu.graph import checkpoint as ckpt_mod
from keto_tpu.relationtuple import RelationTuple
from keto_tpu.replication.digest import compute_digest, diff_digests
from keto_tpu.store import DurableTupleStore, InMemoryTupleStore, WalError
from keto_tpu.store import recover_store
from keto_tpu.store.wal import inject_bitrot, sealed_segments, verify_segment

t = RelationTuple.from_string


@pytest.fixture(autouse=True)
def _clean_faults():
    FAULTS.reset()
    yield
    FAULTS.reset()


def _rbac_store():
    store = InMemoryTupleStore()
    tuples = []
    for g in range(3):
        tuples.append(t(f"n:doc{g}#view@(n:group{g}#member)"))
        for u in range(4):
            tuples.append(t(f"n:group{g}#member@user{g}_{u}"))
    tuples.append(t("n:group0#member@(n:group1#member)"))
    store.write_relation_tuples(*tuples)
    return store


def _engine_rig():
    store = _rbac_store()
    eng = ClosureCheckEngine(SnapshotManager(store), max_depth=5)
    oracle = CheckEngine(store, max_depth=5)
    reqs = [
        t(f"n:doc{g}#view@user{h}_{u}")
        for g in range(3)
        for h in range(3)
        for u in range(4)
    ]
    return store, eng, oracle, reqs


def _daemon(store, eng=None, oracle=None, **kw):
    kw.setdefault("interval_s", 999.0)
    kw.setdefault("sample_rows", 4096)
    kw.setdefault("seed", 3)
    return ScrubDaemon(
        engine_fn=(lambda: eng),
        store_fn=(lambda: store),
        oracle_fn=(lambda: oracle) if oracle is not None else None,
        version_fn=lambda: store.version,
        **kw,
    )


# -- clean cycles --------------------------------------------------------------


class TestCleanCycle:
    def test_clean_cycle_is_a_noop(self):
        store, eng, oracle, reqs = _engine_rig()
        eng.batch_check(reqs)
        daemon = _daemon(store, eng, oracle)
        ev = daemon.step()
        assert ev["clean"]
        assert daemon.repairs == {}
        assert daemon.mismatches == {}
        assert daemon.last_clean_version == store.version
        # a clean cycle is not news: nothing lands in the history ring
        assert daemon.history() == []

    def test_last_clean_version_tracks_the_store(self):
        store, eng, oracle, reqs = _engine_rig()
        daemon = _daemon(store, eng, oracle)
        daemon.step()
        v1 = daemon.last_clean_version
        store.write_relation_tuples(t("n:group0#member@newcomer"))
        daemon.step()
        assert daemon.last_clean_version == store.version > v1

    def test_disabled_daemon_does_nothing(self):
        store, eng, oracle, _ = _engine_rig()
        daemon = _daemon(store, eng, oracle, enabled_fn=lambda: False)
        ev = daemon.step()
        assert ev["action"] == "disabled"
        assert daemon.cycles == 0


# -- (a) device residency ------------------------------------------------------


class TestDeviceScrub:
    def test_bitflip_detected_and_repaired_byte_identical(self):
        store, eng, oracle, reqs = _engine_rig()
        baseline = oracle.batch_check(reqs)
        assert eng.batch_check(reqs) == baseline
        daemon = _daemon(store, eng, oracle)

        FAULTS.arm("scrub.device_bitflip")
        ev = daemon.step()
        assert not ev["clean"]
        assert daemon.mismatches[KIND_DEVICE] >= 1
        assert daemon.repairs[ACTION_RESET_RESIDENCY] == 1
        # post-repair: the engine answers byte-identically to the oracle
        assert eng.batch_check(reqs) == baseline
        assert daemon.step()["clean"]

    def test_scrub_skips_while_residency_is_stale(self):
        """A store that moved past the resident closure is not scanned —
        comparing a v2 host BFS against v1 device rows would page on
        every write. The next rebuild re-arms the scan."""
        store, eng, oracle, reqs = _engine_rig()
        eng.batch_check(reqs)
        store.write_relation_tuples(t("n:group1#member@late_joiner"))
        assert eng.scrub_residency(16, np.random.default_rng(0)) is None
        daemon = _daemon(store, eng, oracle)
        ev = daemon.step()  # must not crash, must not flag device
        assert KIND_DEVICE not in daemon.mismatches
        assert ev["clean"]

    def test_mismatch_event_lands_in_history(self):
        store, eng, oracle, reqs = _engine_rig()
        eng.batch_check(reqs)  # build the residency the fault poisons
        daemon = _daemon(store, eng, oracle)
        FAULTS.arm("scrub.device_bitflip")
        daemon.step()
        events = daemon.history()
        assert events and events[0]["action"] == "cycle"
        kinds = {f.get("kind") for f in events[0]["findings"]}
        assert KIND_DEVICE in kinds


# -- (b) oracle replay ---------------------------------------------------------


class TestReplayScrub:
    def test_poisoned_answer_caught_and_caches_flushed(self):
        store, eng, oracle, reqs = _engine_rig()
        flushed = []
        daemon = _daemon(
            store, eng, oracle, cache_flush_fn=lambda: flushed.append(1)
        )
        truth = oracle.batch_check(reqs)
        # live path served the WRONG answer for one request
        served = list(truth)
        served[0] = not served[0]
        daemon.observe_batch(reqs, served)
        ev = daemon.step()
        assert not ev["clean"]
        assert daemon.mismatches[KIND_REPLAY] == 1
        assert daemon.repairs[ACTION_CACHE_FLUSH] == 1
        assert flushed  # the poisoned-cache seam actually ran
        # the reservoir is dropped with the repair: nothing left to
        # re-flag a second time
        assert daemon.step()["clean"]

    def test_correct_answers_replay_clean(self):
        store, eng, oracle, reqs = _engine_rig()
        daemon = _daemon(store, eng, oracle)
        daemon.observe_batch(reqs, oracle.batch_check(reqs))
        ev = daemon.step()
        assert ev["clean"]
        assert KIND_REPLAY not in daemon.mismatches

    def test_stale_version_entries_are_not_replayed(self):
        """Answers observed at version v are meaningless at v+1 — a
        write in between legitimately changes them."""
        store, eng, oracle, reqs = _engine_rig()
        daemon = _daemon(store, eng, oracle)
        served = oracle.batch_check(reqs)
        served[0] = not served[0]  # would flag if replayed
        daemon.observe_batch(reqs, served)
        store.write_relation_tuples(t("n:group2#member@drive_by"))
        ev = daemon.step()
        assert ev["clean"]
        assert KIND_REPLAY not in daemon.mismatches

    def test_reservoir_is_bounded(self):
        store, eng, oracle, reqs = _engine_rig()
        daemon = _daemon(store, eng, oracle, reservoir=8)
        truth = oracle.batch_check(reqs)
        for _ in range(20):
            daemon.observe_batch(reqs, truth)
        assert len(daemon._reservoir) == 8


# -- (c+d) WAL + checkpoint ----------------------------------------------------


def _durable(tmp_path, n=40, segment_bytes=512):
    store = DurableTupleStore(
        InMemoryTupleStore(),
        str(tmp_path / "wal"),
        sync="always",
        segment_bytes=segment_bytes,
    )
    for i in range(n):
        store.write_relation_tuples(t(f"n:doc{i}#view@user{i}"))
    return store


class TestWalScrub:
    def test_verify_segment_flags_bitrot(self, tmp_path):
        store = _durable(tmp_path)
        sealed = sealed_segments(store.wal_dir)
        assert sealed
        for _, path in sealed:
            assert verify_segment(path)["ok"]
        damaged = inject_bitrot(store.wal_dir)
        res = verify_segment(damaged)
        assert not res["ok"]
        assert res["bad_frames"] or res["gap"]

    def test_bitrot_detected_and_durability_reanchored(self, tmp_path):
        store = _durable(tmp_path)
        daemon = _daemon(store, wal_segments_per_cycle=64)
        FAULTS.arm("wal.bitrot")
        ev = daemon.step()
        assert not ev["clean"]
        assert daemon.mismatches[KIND_WAL] >= 1
        assert daemon.repairs[ACTION_CHECKPOINT_REBUILD] == 1
        # cold recovery from what remains on disk reproduces the live
        # store exactly: the repair checkpoint superseded the damage
        scratch = InMemoryTupleStore()
        report = recover_store(scratch, store.wal_dir, store.checkpoint_dir)
        assert not report.gap
        assert scratch.version == store.version
        assert set(scratch.all_tuples()) == set(store.all_tuples())
        assert daemon.step()["clean"]

    def test_enospc_append_is_never_acked(self, tmp_path):
        """An ENOSPC'd WAL append must propagate (the write is NOT
        acked), fail-stop the wrapper, and fire the append-error hook
        with the errno — the seam keto_wal_append_errors_total{errno}
        hangs off."""
        store = _durable(tmp_path, n=3)
        errnos = []
        store.append_error_cb = errnos.append
        v_before = store.version
        FAULTS.arm("wal.enospc")
        with pytest.raises(OSError) as ei:
            store.write_relation_tuples(t("n:doc99#view@mallory"))
        assert ei.value.errno == 28
        assert errnos == [28]
        # fail-stopped: no further writes, even with space back
        with pytest.raises(WalError, match="fail-stop"):
            store.write_relation_tuples(t("n:doc100#view@mallory"))
        # recovery sees only the acked prefix
        scratch = InMemoryTupleStore()
        recover_store(scratch, store.wal_dir, store.checkpoint_dir)
        assert scratch.version == v_before
        assert t("n:doc99#view@mallory") not in set(scratch.all_tuples())


class TestCheckpointScrub:
    def test_corrupt_checkpoint_detected_and_rebuilt(self, tmp_path):
        store = _durable(tmp_path, n=10, segment_bytes=1 << 20)
        path = store.checkpoint_now()
        with open(path, "r+b") as f:
            f.truncate(os.path.getsize(path) // 2)
        daemon = _daemon(store)
        ev = daemon.step()
        assert not ev["clean"]
        assert daemon.mismatches[KIND_CHECKPOINT] == 1
        assert daemon.repairs[ACTION_CHECKPOINT_REBUILD] == 1
        # the rebuilt newest checkpoint loads clean
        newest = ckpt_mod.list_checkpoints(store.checkpoint_dir)[-1][1]
        ck = ckpt_mod.load_checkpoint(newest)
        assert ck.version == store.version
        ck.close()
        assert daemon.step()["clean"]


# -- freeze / thaw -------------------------------------------------------------


class _FakeSLO:
    alert_burn_rate = 2.0
    fast_window_s = 300.0

    def __init__(self):
        self.rate = 0.0

    def burn_rate(self, window_s):
        return self.rate


class TestFreezeThaw:
    def test_slo_burn_freezes_then_thaws(self):
        store, eng, oracle, reqs = _engine_rig()
        slo = _FakeSLO()
        daemon = _daemon(store, eng, oracle, slo=slo)
        slo.rate = 5.0
        ev = daemon.step()
        assert ev["action"] == "frozen" and ev["reason"] == "slo_burn"
        assert daemon.cycles == 0  # frozen covers the WHOLE cycle
        # transition-only emission: a second frozen tick is not news
        daemon.step()
        assert len(daemon.history()) == 1
        slo.rate = 0.0
        ev = daemon.step()
        assert ev["action"] == "cycle" and ev["clean"]
        assert daemon.cycles == 1

    def test_guard_freeze_blocks_repairs_not_just_moves(self):
        store, eng, oracle, reqs = _engine_rig()
        eng.batch_check(reqs)
        frozen = [True]
        daemon = _daemon(
            store, eng, oracle,
            guards=(lambda: "hbm_pressure" if frozen[0] else None,),
        )
        FAULTS.arm("scrub.device_bitflip")
        ev = daemon.step()
        assert ev["action"] == "frozen" and ev["reason"] == "hbm_pressure"
        assert daemon.repairs == {}  # no repair traffic under pressure
        frozen[0] = False
        daemon.step()  # the armed fault fires and is repaired now
        assert daemon.repairs.get(ACTION_RESET_RESIDENCY) == 1


# -- repair budget -------------------------------------------------------------


class TestRepairBudget:
    def test_budget_limits_repairs_per_cycle(self):
        store, eng, oracle, reqs = _engine_rig()
        eng.batch_check(reqs)
        daemon = _daemon(store, eng, oracle, max_repairs_per_cycle=1)
        FAULTS.arm("scrub.device_bitflip")
        ev = daemon.step()
        # device mismatch wants reset_residency AND cache_flush; only
        # the first fits the budget, the second is recorded as deferred
        assert daemon.repairs.get(ACTION_RESET_RESIDENCY) == 1
        assert ACTION_CACHE_FLUSH not in daemon.repairs
        deferred = [
            f for f in ev["findings"]
            if f.get("reason") == "repair_budget"
        ]
        assert deferred and deferred[0]["action"] == ACTION_CACHE_FLUSH


# -- anti-entropy digest math --------------------------------------------------


class TestDigestMath:
    def _store_with(self, *tuples):
        s = InMemoryTupleStore()
        for tpl in tuples:
            s.write_relation_tuples(tpl)
        return s

    def test_chunk_boundaries(self):
        rows = [t(f"n:doc{i:03d}#view@user{i}") for i in range(6)]
        s = self._store_with(*rows)
        assert len(compute_digest(s, chunk_size=2)["chunks"]) == 3
        assert len(compute_digest(s, chunk_size=3)["chunks"]) == 2
        assert len(compute_digest(s, chunk_size=4)["chunks"]) == 2
        assert len(compute_digest(s, chunk_size=100)["chunks"]) == 1
        d = compute_digest(s, chunk_size=6)
        assert len(d["chunks"]) == 1 and d["count"] == 6

    def test_insertion_order_does_not_matter(self):
        rows = [t(f"n:doc{i}#view@user{i}") for i in range(5)]
        a = self._store_with(*rows)
        b = self._store_with(*reversed(rows))
        da, db = compute_digest(a, chunk_size=2), compute_digest(b, chunk_size=2)
        assert da["chunks"] == db["chunks"]
        assert diff_digests(da, db) == []

    def test_unicode_subjects_digest_stably(self):
        rows = [
            t("n:доc#view@ユーザー"),
            t("n:doc#view@üser"),
            t("n:doc#view@(n:gröup#member)"),
        ]
        a = self._store_with(*rows)
        b = self._store_with(*reversed(rows))
        assert compute_digest(a)["chunks"] == compute_digest(b)["chunks"]

    def test_tombstones_converge_on_content(self):
        """insert+delete and never-inserted agree on chunks: the digest
        hashes live content, not history (versions differ — the version
        field is the compare-at-equal-versions guard, not the hash)."""
        keep = t("n:doc#view@alice")
        ghost = t("n:doc#view@mallory")
        a = self._store_with(keep, ghost)
        a.delete_relation_tuples(ghost)
        b = self._store_with(keep)
        da, db = compute_digest(a), compute_digest(b)
        assert da["chunks"] == db["chunks"]
        assert da["version"] != db["version"]

    def test_diff_pinpoints_divergent_chunk(self):
        rows = [t(f"n:doc{i:03d}#view@user{i}") for i in range(8)]
        a = self._store_with(*rows)
        b = self._store_with(*rows)
        b.delete_relation_tuples(rows[5])  # lands in chunk index 2
        da, db = compute_digest(a, chunk_size=2), compute_digest(b, chunk_size=2)
        assert diff_digests(da, db) != []
        assert all(0 <= i < 4 for i in diff_digests(da, db))

    def test_diff_handles_length_mismatch(self):
        rows = [t(f"n:doc{i}#view@user{i}") for i in range(4)]
        a = self._store_with(*rows)
        b = self._store_with(*rows[:2])
        da, db = compute_digest(a, chunk_size=2), compute_digest(b, chunk_size=2)
        assert 1 in diff_digests(da, db)  # the trailing chunk b lacks


# -- result-cache clear --------------------------------------------------------


class TestCacheClear:
    def test_clear_drops_entries_and_version_stamp(self):
        cache = CheckResultCache(capacity=16)
        cache.get(7, "k")  # first get at a version sets the stamp
        cache.put(7, "k", True)
        assert cache.get(7, "k") is True
        cache.clear()
        # same version, same key: a poisoned answer cached under an
        # UNCHANGED version must not survive the scrubber's flush
        assert cache.get(7, "k") is None


# -- keto doctor ---------------------------------------------------------------


class TestDoctor:
    def test_clean_store_exits_zero(self, tmp_path):
        store = _durable(tmp_path, n=10)
        store.checkpoint_now()
        store.close_durable()
        res = CliRunner().invoke(
            cli,
            ["doctor", "--wal-dir", str(tmp_path / "wal"),
             "--format", "json"],
        )
        assert res.exit_code == 0, res.output
        report = json.loads(res.output)
        assert report["ok"]
        assert report["wal"]["ok"] and report["checkpoints"]["ok"]

    def test_corrupt_sealed_segment_exits_one(self, tmp_path):
        store = _durable(tmp_path, n=40)
        # close the WAL handle WITHOUT close_durable: its final
        # checkpoint would prune the sealed segments we need to damage
        store.wal.close()
        assert inject_bitrot(str(tmp_path / "wal"))
        res = CliRunner().invoke(
            cli,
            ["doctor", "--wal-dir", str(tmp_path / "wal"),
             "--format", "json"],
        )
        assert res.exit_code == 1, res.output
        assert not json.loads(res.output)["ok"]

    def test_missing_wal_dir_exits_two(self, tmp_path):
        res = CliRunner().invoke(
            cli, ["doctor", "--wal-dir", str(tmp_path / "nope")]
        )
        assert res.exit_code == 2


# -- end-to-end visibility -----------------------------------------------------


@pytest.fixture(scope="module")
def scrub_server():
    import httpx  # noqa: F401  (skip the fixture when httpx is absent)

    from keto_tpu.driver import Config
    from tests.test_api_server import ServerFixture

    cfg = Config(
        values={
            "namespaces": [{"id": 1, "name": "n"}],
            "log": {"level": "error"},
            "serve": {
                "read": {"port": 0, "host": "127.0.0.1"},
                "write": {"port": 0, "host": "127.0.0.1"},
            },
            # enabled, but on a tick it will never reach on its own —
            # the test drives cycles deterministically via step()
            "scrub": {"enabled": True, "interval_s": 600.0},
        },
        env={},
    )
    s = ServerFixture(cfg)
    yield s
    s.stop()


class TestEndToEndVisibility:
    """One injected fault visible on all three surfaces at once —
    /debug/scrub, the flight recorder (kind=scrub), and
    keto_scrub_mismatches_total — through a live server."""

    def test_mismatch_visible_in_debug_flight_and_metrics(
        self, scrub_server
    ):
        import httpx

        reg = scrub_server.registry
        daemon = reg._scrubber
        assert daemon is not None and daemon.snapshot()["running"]
        base = f"http://127.0.0.1:{scrub_server.read_port}"
        wbase = f"http://127.0.0.1:{scrub_server.write_port}"
        # subject-set indirection so the closure interior is non-empty —
        # a direct-only graph has no resident rows to scrub
        for body in (
            {
                "namespace": "n", "object": "doc", "relation": "view",
                "subject_set": {
                    "namespace": "n", "object": "g", "relation": "member",
                },
            },
            {
                "namespace": "n", "object": "g", "relation": "member",
                "subject_id": "alice",
            },
        ):
            httpx.put(
                f"{wbase}/relation-tuples", json=body, timeout=30
            ).raise_for_status()
        # a live check builds the residency AND lands in the reservoir
        # through the batcher's scrub_observer tap
        r = httpx.get(
            f"{base}/check",
            params={
                "namespace": "n", "object": "doc", "relation": "view",
                "subject_id": "alice",
            },
            timeout=30,
        )
        assert r.status_code == 200
        assert len(daemon._reservoir) >= 1

        # the write above landed through the overlay (which patches D in
        # place); the row scrub only runs against a quiescent residency,
        # so force the rebuild a background refresh would do
        reg._check_engine.reset_residency()
        FAULTS.arm("scrub.device_bitflip")
        ev = daemon.step()
        assert not ev["clean"]

        # surface 1: /debug/scrub
        doc = httpx.get(f"{base}/debug/scrub", timeout=30).json()
        assert doc["enabled"] and doc["running"]
        assert doc["mismatches"].get(KIND_DEVICE, 0) >= 1
        assert doc["repairs"].get(ACTION_RESET_RESIDENCY, 0) >= 1
        assert doc["history"][0]["action"] == "cycle"
        # surface 2: the flight recorder
        recs = httpx.get(
            f"{base}/debug/flight", params={"n": 200}, timeout=30
        ).json()["records"]
        assert any(rec.get("kind") == "scrub" for rec in recs)
        # surface 3: the metrics plane
        text = httpx.get(f"{base}/metrics", timeout=30).text
        assert "keto_scrub_cycles_total" in text
        assert 'keto_scrub_mismatches_total{kind="device"}' in text
        assert "keto_scrub_last_clean_version" in text
        # and the repair held: the same check still answers correctly
        r = httpx.get(
            f"{base}/check",
            params={
                "namespace": "n", "object": "doc", "relation": "view",
                "subject_id": "alice",
            },
            timeout=30,
        )
        assert r.status_code == 200 and r.json()["allowed"] is True
