"""Benchmark ladder: batched Check/Expand throughput on the closure engine.

Runs the BASELINE.json config ladder (as far as one chip + host RAM allow):

- ``rbac1m``   — synthetic RBAC, 1M tuples (users->groups->roles->grants).
- ``github10m``— GitHub-style, 10M tuples: users/teams/orgs/repos, team
  nesting, per-repo permission grants; mixed Check + Expand traffic.
- ``rbac100m`` — 100M-tuple RBAC (BASELINE north-star scale); opt-in via
  BENCH_SCALE=100m (build takes minutes).

Each config reports object-path RPS (full RelationTuple encode, what a
transport handler pays), array-path RPS (check_ids, what array-native /
sharded tiers pay), p50/p95 batch latency, expand p95, and build times.

Prints ONE json line (the largest completed config's object-path RPS):
  {"metric": "check_rps", "value": N, "unit": "checks/s", "vs_baseline": x}
vs_baseline is relative to the BASELINE.json north star of 1,000,000
check RPCs/sec (the reference publishes no measured numbers — SURVEY.md §6).

Env knobs: BENCH_CONFIGS (csv; default "rbac1m,github10m"), BENCH_SCALE
(=100m appends rbac100m), BENCH_BATCH (default 4096), BENCH_ITERS (default
30), BENCH_ENGINE (closure|device, default closure).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


# ---------------------------------------------------------------------------
# graph generators (columnar bulk: node-key pools, no tuple objects)
# ---------------------------------------------------------------------------


def gen_rbac(n_tuples: int, rng: np.random.Generator):
    """users ∈ groups ∈ roles -> per-resource grants (BASELINE 'rbac')."""
    from keto_tpu.store import ColumnarTupleStore

    n_users = max(n_tuples // 10, 100)
    n_groups = max(n_tuples // 100, 20)
    n_roles = max(n_groups // 10, 5)
    n_resources = max(n_tuples // 3, 50)

    users = [(f"u{i}",) for i in range(n_users)]
    groups = [("rbac", f"g{i}", "member") for i in range(n_groups)]
    roles = [("rbac", f"role{i}", "member") for i in range(n_roles)]
    resources = [("rbac", f"res{i}", "view") for i in range(n_resources)]

    src, dst = [], []
    # users -> groups (~40%)
    k = int(n_tuples * 0.4)
    src += [groups[i] for i in rng.integers(n_groups, size=k)]
    dst += [users[i] for i in rng.integers(n_users, size=k)]
    # groups -> roles (~10%)
    k = int(n_tuples * 0.1)
    src += [roles[i] for i in rng.integers(n_roles, size=k)]
    dst += [groups[i] for i in rng.integers(n_groups, size=k)]
    # role hierarchy (~5%)
    k = int(n_tuples * 0.05)
    src += [roles[i] for i in rng.integers(n_roles, size=k)]
    dst += [roles[i] for i in rng.integers(n_roles, size=k)]
    # resource grants -> roles or groups (~45%)
    k = n_tuples - len(src)
    src += [resources[i] for i in rng.integers(n_resources, size=k)]
    half = rng.random(k) < 0.5
    role_pick = rng.integers(n_roles, size=k)
    group_pick = rng.integers(n_groups, size=k)
    dst += [
        roles[role_pick[i]] if half[i] else groups[group_pick[i]]
        for i in range(k)
    ]

    store = ColumnarTupleStore()
    store.bulk_load_edges(src, dst)

    def sample(rng, k):
        s = [resources[i] for i in rng.integers(n_resources, size=k)]
        d = [users[i] for i in rng.integers(n_users, size=k)]
        return s, d

    expand_roots = [resources[i] for i in rng.integers(n_resources, size=256)]
    return store, sample, expand_roots


def gen_github(n_tuples: int, rng: np.random.Generator):
    """GitHub-style: team membership + nesting, per-repo permission grants
    to teams or direct collaborators (BASELINE 'github' mixed config)."""
    from keto_tpu.store import ColumnarTupleStore

    n_users = max(n_tuples // 8, 100)
    n_teams = max(n_tuples // 400, 20)  # realistically few teams
    n_repos = max(n_tuples // 3, 50)
    perms = ("pull", "triage", "push", "admin")

    users = [(f"u{i}",) for i in range(n_users)]
    teams = [("gh", f"team{i}", "member") for i in range(n_teams)]
    repo_perm = [
        ("gh", f"repo{i}", p) for i in range(n_repos) for p in perms
    ]

    src, dst = [], []
    # team membership (~45%)
    k = int(n_tuples * 0.45)
    src += [teams[i] for i in rng.integers(n_teams, size=k)]
    dst += [users[i] for i in rng.integers(n_users, size=k)]
    # team nesting (~3%)
    k = int(n_tuples * 0.03)
    src += [teams[i] for i in rng.integers(n_teams, size=k)]
    dst += [teams[i] for i in rng.integers(n_teams, size=k)]
    # repo permission grants (~52%): 80% to teams, 20% direct collaborators
    k = n_tuples - len(src)
    src += [repo_perm[i] for i in rng.integers(len(repo_perm), size=k)]
    to_team = rng.random(k) < 0.8
    team_pick = rng.integers(n_teams, size=k)
    user_pick = rng.integers(n_users, size=k)
    dst += [
        teams[team_pick[i]] if to_team[i] else users[user_pick[i]]
        for i in range(k)
    ]

    store = ColumnarTupleStore()
    store.bulk_load_edges(src, dst)

    pull_perms = [("gh", f"repo{i}", "pull") for i in range(n_repos)]

    def sample(rng, k):
        s = [pull_perms[i] for i in rng.integers(n_repos, size=k)]
        d = [users[i] for i in rng.integers(n_users, size=k)]
        return s, d

    expand_roots = [pull_perms[i] for i in rng.integers(n_repos, size=256)]
    return store, sample, expand_roots


# ---------------------------------------------------------------------------
# measurement
# ---------------------------------------------------------------------------


def run_config(name: str, n_tuples: int, gen, batch: int, iters: int, engine_kind: str):
    from keto_tpu.engine.device import DeviceCheckEngine, SnapshotExpandEngine
    from keto_tpu.engine.closure import ClosureCheckEngine
    from keto_tpu.graph import SnapshotManager
    from keto_tpu.relationtuple import RelationTuple, SubjectID, SubjectSet

    rng = np.random.default_rng(7)
    t0 = time.time()
    store, sample, expand_roots = gen(n_tuples, rng)
    t_build = time.time() - t0

    t0 = time.time()
    snapshots = SnapshotManager(store)
    snap = snapshots.snapshot()
    t_encode = time.time() - t0

    if engine_kind == "device":
        engine = DeviceCheckEngine(snapshots, max_depth=5)
    else:
        engine = ClosureCheckEngine(
            snapshots, max_depth=5, interior_limit=32768
        )

    def to_requests(skeys, dkeys):
        return [
            RelationTuple(
                namespace=s[0],
                object=s[1],
                relation=s[2],
                subject=SubjectID(d[0])
                if len(d) == 1
                else SubjectSet(namespace=d[0], object=d[1], relation=d[2]),
            )
            for s, d in zip(skeys, dkeys)
        ]

    warm = to_requests(*sample(rng, batch))
    t0 = time.time()
    engine.batch_check(warm)  # closure build + compile
    t_first = time.time() - t0
    engine.batch_check(warm)

    # object path: full RelationTuple encode per request
    lat = []
    n_allowed = 0
    batches = [to_requests(*sample(rng, batch)) for _ in range(iters)]
    t_all = time.time()
    for reqs in batches:
        t0 = time.time()
        n_allowed += sum(engine.batch_check(reqs))
        lat.append(time.time() - t0)
    obj_elapsed = time.time() - t_all
    obj_rps = batch * iters / obj_elapsed

    # array path: pre-encoded ids (array-native clients / sharded tier)
    enc_rps = None
    if hasattr(engine, "check_ids"):
        lookup = snap.vocab.lookup
        dummy = snap.dummy_node
        enc_batches = []
        for _ in range(iters):
            skeys, dkeys = sample(rng, batch)
            s_ids = np.array(
                [v if (v := lookup(k)) is not None else dummy for k in skeys],
                np.int64,
            )
            d_ids = np.array(
                [v if (v := lookup(k)) is not None else dummy for k in dkeys],
                np.int64,
            )
            is_id = np.fromiter(
                (len(k) == 1 for k in dkeys), bool, count=batch
            )
            enc_batches.append((s_ids, d_ids, is_id))
        engine.check_ids(*enc_batches[0])
        t0 = time.time()
        for s_ids, d_ids, is_id in enc_batches:
            engine.check_ids(s_ids, d_ids, is_id)
        enc_rps = batch * iters / (time.time() - t0)

    # expand: host tree walk over the resident CSR
    expander = SnapshotExpandEngine(snapshots, max_depth=5)
    exp_lat = []
    for key in expand_roots:
        subject = SubjectSet(namespace=key[0], object=key[1], relation=key[2])
        t0 = time.time()
        expander.build_tree(subject, max_depth=3)
        exp_lat.append(time.time() - t0)

    meta = {
        "config": name,
        "tuples": n_tuples,
        "nodes": snap.num_nodes,
        "padded_edges": snap.padded_edges,
        "batch": batch,
        "iters": iters,
        "engine": engine_kind,
        "build_s": round(t_build, 2),
        "encode_s": round(t_encode, 2),
        "first_batch_s": round(t_first, 2),
        "check_rps": round(obj_rps),
        "check_rps_encoded": round(enc_rps) if enc_rps else None,
        "batch_p50_ms": round(1000 * float(np.percentile(lat, 50)), 2),
        "batch_p95_ms": round(1000 * float(np.percentile(lat, 95)), 2),
        "expand_p50_ms": round(1000 * float(np.percentile(exp_lat, 50)), 3),
        "expand_p95_ms": round(1000 * float(np.percentile(exp_lat, 95)), 3),
        "allowed_frac": round(n_allowed / (batch * iters), 3),
    }
    if hasattr(engine, "_cached") and engine._cached is not None:
        meta["interior_nodes"] = int(engine._cached.ig.m)
    print(json.dumps(meta), file=sys.stderr, flush=True)
    return meta


CONFIGS = {
    "rbac1m": (1_000_000, gen_rbac),
    "github10m": (10_000_000, gen_github),
    "rbac100m": (100_000_000, gen_rbac),
}


def main():
    import jax

    batch = int(os.environ.get("BENCH_BATCH", 4096))
    iters = int(os.environ.get("BENCH_ITERS", 30))
    engine_kind = os.environ.get("BENCH_ENGINE", "closure")
    names = os.environ.get("BENCH_CONFIGS", "rbac1m,github10m").split(",")
    if os.environ.get("BENCH_SCALE") == "100m" and "rbac100m" not in names:
        names.append("rbac100m")

    print(
        json.dumps({"device": str(jax.devices()[0])}),
        file=sys.stderr,
        flush=True,
    )
    results = []
    for name in names:
        name = name.strip()
        if name not in CONFIGS:
            print(
                f"unknown BENCH_CONFIGS entry {name!r}; known: "
                f"{sorted(CONFIGS)}",
                file=sys.stderr,
            )
            continue
        n, gen = CONFIGS[name]
        results.append(run_config(name, n, gen, batch, iters, engine_kind))

    if not results:
        print("no valid bench configs ran", file=sys.stderr)
        sys.exit(1)
    primary = results[-1]  # largest completed config
    print(
        json.dumps(
            {
                "metric": "check_rps",
                "value": primary["check_rps"],
                "unit": "checks/s",
                "vs_baseline": round(primary["check_rps"] / 1_000_000, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
