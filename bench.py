"""Benchmark: batched Check throughput on the device engine.

Builds a synthetic RBAC-shaped tuple graph (users -> groups -> roles ->
resource grants, BASELINE.json's "rbac" config family), then measures
steady-state batched check RPS through DeviceCheckEngine on whatever
device JAX gives (real TPU chip under the driver).

Prints ONE json line:
  {"metric": "check_rps", "value": N, "unit": "checks/s", "vs_baseline": x}
vs_baseline is relative to the BASELINE.json north star of 1,000,000
check RPCs/sec (the reference publishes no measured numbers — SURVEY.md §6).

Env knobs: BENCH_TUPLES (default 1_000_000), BENCH_BATCH (default 4096),
BENCH_ITERS (default 20), BENCH_MODE (auto|dense|scatter).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def build_rbac_graph(n_tuples: int, rng: np.random.Generator):
    """users ∈ groups ∈ roles -> per-resource grants, with ~15% subject-set
    indirection depth beyond 2 (role hierarchies)."""
    from keto_tpu.relationtuple import RelationTuple, SubjectID, SubjectSet
    from keto_tpu.store import InMemoryTupleStore

    n_users = max(n_tuples // 10, 100)
    n_groups = max(n_tuples // 100, 20)
    n_roles = max(n_groups // 10, 5)
    n_resources = max(n_tuples // 3, 50)

    tuples: list[RelationTuple] = []
    # users -> groups  (~40%)
    for _ in range(int(n_tuples * 0.4)):
        tuples.append(
            RelationTuple(
                "rbac", f"g{rng.integers(n_groups)}", "member",
                SubjectID(f"u{rng.integers(n_users)}"),
            )
        )
    # groups -> roles (~10%)
    for _ in range(int(n_tuples * 0.1)):
        tuples.append(
            RelationTuple(
                "rbac", f"role{rng.integers(n_roles)}", "member",
                SubjectSet("rbac", f"g{rng.integers(n_groups)}", "member"),
            )
        )
    # role hierarchy (~5%)
    for _ in range(int(n_tuples * 0.05)):
        a, b = rng.integers(n_roles, size=2)
        tuples.append(
            RelationTuple(
                "rbac", f"role{a}", "member",
                SubjectSet("rbac", f"role{b}", "member"),
            )
        )
    # resource grants -> roles or groups (~45%)
    while len(tuples) < n_tuples:
        r = rng.integers(n_resources)
        if rng.random() < 0.5:
            sub = SubjectSet("rbac", f"role{rng.integers(n_roles)}", "member")
        else:
            sub = SubjectSet("rbac", f"g{rng.integers(n_groups)}", "member")
        tuples.append(RelationTuple("rbac", f"res{r}", "view", sub))

    store = InMemoryTupleStore()
    store.write_relation_tuples(*tuples)
    return store, n_users, n_resources


def main():
    n_tuples = int(os.environ.get("BENCH_TUPLES", 1_000_000))
    batch = int(os.environ.get("BENCH_BATCH", 4096))
    iters = int(os.environ.get("BENCH_ITERS", 20))
    mode = os.environ.get("BENCH_MODE", "auto")

    import jax

    from keto_tpu.engine.device import DeviceCheckEngine
    from keto_tpu.graph import SnapshotManager
    from keto_tpu.relationtuple import RelationTuple, SubjectID

    rng = np.random.default_rng(7)
    t0 = time.time()
    store, n_users, n_resources = build_rbac_graph(n_tuples, rng)
    t_build = time.time() - t0

    t0 = time.time()
    snapshots = SnapshotManager(store)
    snap = snapshots.snapshot()
    t_encode = time.time() - t0

    engine = DeviceCheckEngine(snapshots, max_depth=5, mode=mode)

    # request mix: resource-view checks for random users (the Zanzibar hot
    # query), ~70% expected denials like production check traffic
    def make_requests(k):
        return [
            RelationTuple(
                "rbac", f"res{rng.integers(n_resources)}", "view",
                SubjectID(f"u{rng.integers(n_users)}"),
            )
            for _ in range(k)
        ]

    warm = make_requests(batch)
    t0 = time.time()
    engine.batch_check(warm)  # compile
    t_compile = time.time() - t0
    engine.batch_check(warm)  # steady-state warm

    batches = [make_requests(batch) for _ in range(iters)]
    t0 = time.time()
    n_allowed = 0
    for reqs in batches:
        res = engine.batch_check(reqs)
        n_allowed += sum(res)
    elapsed = time.time() - t0
    rps = batch * iters / elapsed

    meta = {
        "tuples": n_tuples,
        "nodes": snap.num_nodes,
        "padded_nodes": snap.padded_nodes,
        "padded_edges": snap.padded_edges,
        "batch": batch,
        "iters": iters,
        "device": str(jax.devices()[0]),
        "mode": "dense" if engine._device_graph(snap).dense else "scatter",
        "build_s": round(t_build, 2),
        "encode_s": round(t_encode, 2),
        "compile_s": round(t_compile, 2),
        "allowed_frac": round(n_allowed / (batch * iters), 3),
        "batch_latency_ms": round(1000 * elapsed / iters, 2),
    }
    print(json.dumps(meta), file=sys.stderr)
    print(
        json.dumps(
            {
                "metric": "check_rps",
                "value": round(rps),
                "unit": "checks/s",
                "vs_baseline": round(rps / 1_000_000, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
